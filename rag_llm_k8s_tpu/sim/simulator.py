"""Pure-host scheduler simulator: the decision core on a virtual clock.

``SimEngine`` answers the same narrow surface ``sim/replay.py``'s
``LockstepDriver`` drives against the real ``ContinuousEngine`` —
``admission_state`` / ``free_slots`` / ``admit_many`` / ``step`` /
``drain_preempted`` / ``has_active`` / ``slots`` / ``reset`` /
``buckets`` — but every decision comes from ``sim/policy.py`` (the SAME
functions the live engine delegates to) and every window's duration
comes from a step model instead of a device:

- ``RooflineStepModel`` prices windows analytically from the ledger's
  ``RooflineModel`` (first-principles what-ifs: a TPU you don't have).
- ``CalibratedStepModel.from_journal`` fits per-kind window durations
  from a MEASURED flight journal's ``goodput_window`` events (capacity
  planning anchored to a deployment you do have).

The simulator emits a synthetic flight-schema journal — ``admit``,
``sync_window_open``/``close``, ``block_grow``, ``preempt``, ``eos``,
``goodput_window`` (via a real path-loaded ``GoodputLedger`` fed virtual
durations), ``complete`` — with virtual timestamps, so the existing
renderers (``flightview --summary/--goodput``, ``goodput.render_report``)
consume it unchanged. ``simulate()`` wraps trace → driver → report and
measures the virtual-over-wall speedup (the ≥100× figure the
``replay_fidelity`` bench leg pins).

What the simulator models: the paged one-shot admission path (bucketed
grouped prefill), fixed-horizon decode sync windows, block growth,
pool-exhaustion preemption + resume. What it does not (yet): the
interleaved chunked-prefill planner (``plan_mixed_window`` is pure and
tested, but ``SimEngine`` has no mixed-window executor), speculative
verify windows, and chaos resets — docs/REPLAY.md tracks the gaps.

Import discipline: stdlib-only, no package-internal imports (SIM-PURITY);
siblings and ``obs/goodput.py`` load by file path via
``policy.load_sibling``.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import importlib.util as _ilu
import os as _os


def _load_sibling(name: str):
    here = _os.path.dirname(_os.path.abspath(__file__))
    path = _os.path.normpath(_os.path.join(here, name + ".py"))
    spec = _ilu.spec_from_file_location(
        "_rag_sim_" + _os.path.basename(name), path
    )
    mod = _ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


policy = _load_sibling("policy")
_goodput = policy.load_sibling("../obs/goodput")
_tenants = policy.load_sibling("../obs/tenants")


class PoolExhausted(RuntimeError):
    """Name-matched by the driver's requeue path (duck-typed engines
    cannot share an exception class without a package import)."""


def llama8b_roofline(
    peak_tflops: float = 0.0, hbm_gbs: float = 0.0
) -> "object":
    """A Llama-3-8B-shaped ``RooflineModel`` — the default chip/model
    arithmetic when the caller plans capacity without a config in hand."""
    return _goodput.roofline_for_llama(
        num_layers=32, hidden_size=4096, num_heads=32, num_kv_heads=8,
        head_dim=128, intermediate_size=14336, vocab_size=128256,
        peak_tflops=peak_tflops, hbm_gbs=hbm_gbs,
    )


# ----------------------------------------------------------------------
# step models (virtual window durations)
# ----------------------------------------------------------------------

class RooflineStepModel:
    """Analytic window durations: ``overhead + max(compute, memory)`` at
    a derated fraction of the roofline's peaks — the same FLOPs/bytes
    arithmetic the ledger uses to score real windows, inverted into a
    duration. ``efficiency`` derates both peaks (real kernels don't hit
    the roofline); ``overhead_s`` is the per-window dispatch floor."""

    def __init__(self, roofline, overhead_s: float = 200e-6,
                 efficiency: float = 0.5):
        self.roofline = roofline
        self.overhead_s = max(0.0, float(overhead_s))
        self.efficiency = min(1.0, max(1e-3, float(efficiency)))

    def _dur(self, flops: float, nbytes: float) -> float:
        rf = self.roofline
        eff = self.efficiency
        return self.overhead_s + max(
            flops / (rf.peak_flops * eff), nbytes / (rf.peak_bytes * eff)
        )

    def decode(self, steps: int, useful: int, ctx_tokens: int) -> float:
        rf = self.roofline
        return self._dur(
            rf.flops_per_token * useful,
            steps * (rf.weight_bytes + ctx_tokens * rf.kv_bytes_per_token),
        )

    def prefill(self, bucket: int, rows: int, tokens: int) -> float:
        # padded lanes burn real compute even when they are bubble
        rf = self.roofline
        return self._dur(
            rf.flops_per_token * max(int(bucket) * int(rows), int(tokens)),
            rf.weight_bytes,
        )

    def stall(self) -> float:
        return self.overhead_s


class CalibratedStepModel:
    """Per-kind window durations fitted from a MEASURED journal's
    ``goodput_window`` events: for each kind, a least-squares line
    ``dur_ms = a + b * tokens`` (collapsing to the kind's mean when the
    recording has no token spread). Simulating the recorded deployment
    back through its own fit is the ``replay_fidelity`` bench leg's
    steps/s check; changing the load against the same fit is the
    capacity-planning walkthrough in docs/REPLAY.md."""

    DEFAULT_MS = 1.0

    def __init__(self, coeffs: Dict[str, Tuple[float, float]],
                 stall_ms: float = 0.1):
        self.coeffs = dict(coeffs)
        self.stall_ms = float(stall_ms)

    @classmethod
    def from_journal(cls, events: Iterable[Dict]) -> "CalibratedStepModel":
        samples: Dict[str, List[Tuple[float, float]]] = {}
        stall: List[float] = []
        for e in events:
            if not isinstance(e, dict) or e.get("type") != "goodput_window":
                continue
            dur = float(e.get("dur_ms", 0.0))
            if dur <= 0:
                continue
            tokens = float(e.get("tokens", 0.0))
            if tokens <= 0 and "preempt_rework" in e:
                stall.append(dur)
                continue
            samples.setdefault(e.get("kind", "decode"), []).append(
                (tokens, dur)
            )
        coeffs: Dict[str, Tuple[float, float]] = {}
        for kind, pts in samples.items():
            coeffs[kind] = cls._fit(pts)
        stall_ms = (sum(stall) / len(stall)) if stall else 0.1
        return cls(coeffs, stall_ms=stall_ms)

    @staticmethod
    def _fit(pts: Sequence[Tuple[float, float]]) -> Tuple[float, float]:
        n = len(pts)
        mean_d = sum(d for _, d in pts) / n
        xs = {x for x, _ in pts}
        if n < 2 or len(xs) < 2:
            return (mean_d, 0.0)
        mean_x = sum(x for x, _ in pts) / n
        sxx = sum((x - mean_x) ** 2 for x, _ in pts)
        sxy = sum((x - mean_x) * (d - mean_d) for x, d in pts)
        b = sxy / sxx
        a = mean_d - b * mean_x
        if b < 0:  # noisy recording: a negative slope predicts garbage
            return (mean_d, 0.0)
        return (a, b)

    def _pred_ms(self, kind: str, tokens: float) -> float:
        c = self.coeffs.get(kind)
        if c is None:
            if self.coeffs:  # nearest thing to a prior: the global mean
                c_vals = list(self.coeffs.values())
                c = (sum(a for a, _ in c_vals) / len(c_vals),
                     sum(b for _, b in c_vals) / len(c_vals))
            else:
                return self.DEFAULT_MS
        return max(1e-3, c[0] + c[1] * float(tokens))

    def decode(self, steps: int, useful: int, ctx_tokens: int) -> float:
        return self._pred_ms("decode", useful) / 1e3

    def prefill(self, bucket: int, rows: int, tokens: int) -> float:
        return self._pred_ms("prefill", tokens) / 1e3

    def stall(self) -> float:
        return max(1e-6, self.stall_ms / 1e3)


# ----------------------------------------------------------------------
# the virtual engine
# ----------------------------------------------------------------------

class _SimSlot:
    __slots__ = ("active", "prefilling", "request_id", "tokens",
                 "remaining", "kv_ub", "admit_seq")

    def __init__(self):
        self.active = False
        self.prefilling = False
        self.request_id = -1
        self.tokens: List[int] = []
        self.remaining = 0
        self.kv_ub = 0
        self.admit_seq = 0


class SimEngine:
    """A virtual paged continuous engine: policy decisions + modeled
    durations, no device, no jax. Drives with ``LockstepDriver`` exactly
    like the real engine; every scheduler-visible event lands in
    ``self.journal`` with virtual timestamps (``t`` = seconds of modeled
    chip time since construction)."""

    def __init__(
        self,
        buckets: Sequence[int] = (128, 256, 512),
        max_batch_size: int = 8,
        max_seq_len: int = 1024,
        block_size: int = 16,
        pool_blocks: Optional[int] = None,
        decode_sync_steps: int = 1,
        step_model=None,
        roofline=None,
        chip_hour_usd: float = 0.0,
        eos_token_ids: Sequence[int] = (),
        out_len: Optional[Dict[int, int]] = None,
    ):
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.B = int(max_batch_size)
        self.T = int(max_seq_len)
        self.block_size = int(block_size)
        self.MB = policy.blocks_for(self.T, self.block_size)
        self.pool_blocks = (
            int(pool_blocks) if pool_blocks is not None
            else self.MB * self.B
        )
        self.k = max(1, int(decode_sync_steps))
        rf = roofline if roofline is not None else llama8b_roofline()
        self.ledger = _goodput.GoodputLedger(
            rf, enabled=True, chip_hour_usd=chip_hour_usd
        )
        self.step_model = (
            step_model if step_model is not None
            else RooflineStepModel(rf)
        )
        self.chip_hour_usd = float(chip_hour_usd)
        self.eos_token_ids = frozenset(int(x) for x in eos_token_ids)
        self.out_len: Dict[int, int] = dict(out_len or {})
        self.slots: List[_SimSlot] = [_SimSlot() for _ in range(self.B)]
        self._slot_blocks = [0] * self.B
        self._free_blocks = self.pool_blocks
        self._admit_seq = 0
        self._preempted: List[Tuple[int, List[int]]] = []
        self._rework: set = set()
        self._blocks_at_retire: Dict[int, int] = {}
        self.journal: List[Dict] = []
        self._seq = 0
        self.t = 0.0  # virtual seconds of modeled chip time
        self.windows = 0
        self.decode_steps = 0

    # -- journal ------------------------------------------------------
    def emit(self, etype: str, rid: Optional[int] = None, **attrs) -> None:
        """Flight-schema event with a VIRTUAL timestamp. Also the
        ``emit`` callable handed to the driver, so scheduler-level
        events (arrival/resubmit/complete) interleave in sequence."""
        self._seq += 1
        ev: Dict = {"seq": self._seq, "t": round(self.t, 9), "type": etype}
        if rid is not None:
            ev["rid"] = rid
        ev.update(attrs)
        self.journal.append(ev)

    def _advance(self, dur_s: float, summary: Optional[Dict]) -> None:
        self.t += max(0.0, float(dur_s))
        if summary is not None:
            self.emit("goodput_window", **summary)

    # -- driver surface ------------------------------------------------
    def has_active(self) -> bool:
        return any(s.active for s in self.slots)

    def free_slots(self) -> List[int]:
        return [r for r, s in enumerate(self.slots)
                if not s.active and not s.prefilling]

    def admission_state(self, prompt_len: int) -> str:
        need = policy.admission_blocks(prompt_len, self.block_size)
        verdict, want = policy.admission_verdict(
            need, self.pool_blocks, False, self.MB
        )
        if verdict != "check":
            return verdict
        return "ok" if want <= self._free_blocks else "wait"

    def admit_many(self, items: Sequence[Tuple]) -> List:
        """Grouped one-shot admission, the real scheduler's shape:
        bucket + clamp, chunk by ``policy.admission_chunks``, one modeled
        prefill window per chunk. Per-item results align with ``items``:
        ``(row, finished_or_None)`` or an exception instance."""
        prepared = []
        for j, (rid, prompt, max_new, seed) in enumerate(items):
            p = list(prompt)
            S = policy.bucket_len(len(p), self.buckets)
            if len(p) > S:
                p = p[-S:]  # left-truncate, the engine's discipline
            mx = policy.clamp_max_new(int(max_new), S, self.T)
            prepared.append((j, rid, p, S, mx))
        results: List = [None] * len(items)
        free = iter(self.free_slots())
        for S, member_idx in policy.admission_chunks(
            [(i, e[3]) for i, e in enumerate(prepared)], self.B
        ):
            chunk = [prepared[i] for i in member_idx]
            admitted = []
            for j, rid, p, _, mx in chunk:
                need = policy.admission_blocks(len(p), self.block_size)
                _, want = policy.admission_verdict(
                    need, self.pool_blocks, False, self.MB
                )
                if want > self._free_blocks:
                    results[j] = PoolExhausted(
                        f"sim pool: {want} blocks wanted, "
                        f"{self._free_blocks} free"
                    )
                    continue
                row = next(free)
                self._free_blocks -= want
                self._slot_blocks[row] = want
                admitted.append((j, rid, p, mx, row))
            if not admitted:
                continue
            rows_led = {rid: len(p) for _, rid, p, _, _ in admitted}
            rework = {rid for rid in rows_led if rid in self._rework}
            self._rework -= rework
            dur = self.step_model.prefill(
                S, len(admitted), sum(rows_led.values())
            )
            self._advance(dur, self.ledger.record_prefill(
                dur, S, rows_led, rework=rework
            ))
            for j, rid, p, mx, row in admitted:
                tok0 = self._tok(rid, 0)
                tn = self.ledger.tenant_of(rid)
                self.emit("admit", rid, slot=row, prompt_len=len(p),
                          bucket=S, tok0=tok0,
                          **({"tenant": tn} if tn else {}))
                target = mx
                if rid in self.out_len:  # recorded generation length
                    target = max(1, min(mx, int(self.out_len[rid])))
                if target <= 1:
                    self._blocks_at_retire[rid] = self._slot_blocks[row]
                    self._release_row(row)
                    results[j] = (row, [tok0])
                    continue
                self._admit_seq += 1
                s = self.slots[row]
                s.active = True
                s.request_id = rid
                s.tokens = [tok0]
                s.remaining = target - 1
                s.kv_ub = len(p) + 1
                s.admit_seq = self._admit_seq
                results[j] = (row, None)
        return results

    def step(self) -> List[Tuple[int, List[int]]]:
        """One decode sync window of ``decode_sync_steps`` virtual steps:
        grow block tables (preempting newest-first under exhaustion,
        the live discipline), emit every active row's tokens, retire
        budget-exhausted rows."""
        active = [(r, s) for r, s in enumerate(self.slots) if s.active]
        if not active:
            return []
        # ---- growth (policy.grow_shortfall), preempt on exhaustion ----
        while True:
            active = [(r, s) for r, s in enumerate(self.slots) if s.active]
            if not active:
                dur = self.step_model.stall()
                self._advance(dur, self.ledger.record_preempt_stall(
                    dur, [rid for rid, _ in self._preempted]
                ))
                self.windows += 1
                return []
            short = policy.grow_shortfall(
                ((s.admit_seq, r, s.kv_ub, self._slot_blocks[r])
                 for r, s in active),
                self.k, None, self.block_size, self.MB,
            )
            need = sum(m for _, _, m, _ in short)
            if need <= self._free_blocks:
                for _, row, missing, have in short:
                    self._free_blocks -= missing
                    self._slot_blocks[row] = have + missing
                    self.emit("block_grow", self.slots[row].request_id,
                              blocks=missing, total=have + missing)
                break
            _, victim = policy.preempt_victim(
                (s.admit_seq, r) for r, s in active
            )
            vslot = self.slots[victim]
            self._preempted.append((vslot.request_id, list(vslot.tokens)))
            self.emit("preempt", vslot.request_id,
                      blocks=self._slot_blocks[victim],
                      n_tokens=len(vslot.tokens))
            self._release_row(victim)
        # ---- dispatch + drain (virtual) -------------------------------
        active = [(r, s) for r, s in enumerate(self.slots) if s.active]
        k = self.k
        self.emit("sync_window_open", steps=k, active=len(active))
        ctx = sum(s.kv_ub for _, s in active)
        done: List[Tuple[int, List[int]]] = []
        kept: Dict[int, int] = {}
        for row, s in active:
            take = min(k, s.remaining)
            for i in range(take):
                s.tokens.append(self._tok(s.request_id, len(s.tokens)))
            kept[s.request_id] = take
            s.remaining -= take
            s.kv_ub += take
            if s.remaining <= 0:
                done.append((s.request_id, s.tokens))
                self.emit("eos", s.request_id, reason="budget",
                          n_tokens=len(s.tokens))
                self._blocks_at_retire[s.request_id] = self._slot_blocks[row]
                self._release_row(row)
        dur = self.step_model.decode(k, sum(kept.values()), ctx)
        self._advance(dur, self.ledger.record_decode(
            dur, batch=self.B, steps=k, kept=kept, ctx_tokens=ctx
        ))
        self.emit("sync_window_close", steps=k, done=len(done),
                  duration_ms=round(dur * 1e3, 3))
        self.windows += 1
        self.decode_steps += k
        return done

    def drain_preempted(self) -> List[Tuple[int, List[int]]]:
        out, self._preempted = self._preempted, []
        return out

    def reset(self) -> None:
        for r in range(self.B):
            if self.slots[r].active:
                self._release_row(r)
        self._preempted = []
        self.emit("reset", cause="sim")

    # -- scheduler-optional hooks (getattr-probed by the driver) -------
    def mark_rework(self, rid: int) -> None:
        self._rework.add(rid)

    def discard_request_goodput(self, rid: int) -> None:
        self.ledger.discard_request(rid)

    def pop_request_goodput(self, rid: int,
                            tokens: float = 0.0) -> Optional[Dict]:
        return self.ledger.pop_request(rid, tokens=tokens)

    def pop_blocks_allocated(self, rid: int) -> Optional[int]:
        return self._blocks_at_retire.pop(rid, None)

    # -- internals -----------------------------------------------------
    def _release_row(self, row: int) -> None:
        self._free_blocks += self._slot_blocks[row]
        self._slot_blocks[row] = 0
        self.slots[row] = _SimSlot()

    def _tok(self, rid: int, i: int) -> int:
        t = 11 + ((int(rid) * 2654435761 + i * 40503) % 50021)
        while t in self.eos_token_ids:  # EOS comes from length, not luck
            t += 1
        return t


# ----------------------------------------------------------------------
# disaggregated pool sizing (pool-role awareness)
# ----------------------------------------------------------------------

#: executable kinds that are pure prefill work vs pure decode work; the
#: mixed kinds split per-window by their stamped decode-token share
_PREFILL_KINDS = frozenset({"prefill", "prefill_px"})
_DECODE_KINDS = frozenset({"decode", "verify"})


def split_chip_time(events: Iterable[Dict]) -> Dict[str, float]:
    """Walk a flight journal (real or synthetic) and attribute every
    ``goodput_window``'s duration to the prefill or the decode side of a
    disaggregated deployment. Pure-prefill and pure-decode kinds map
    whole; ``oneshot``/``mixed`` windows (which carry both phases in one
    dispatch) split by their ``decode_tokens``/``tokens`` ratio — the
    same stamps ``state_from_events`` reads, so no model config is
    needed offline. Returns ``{"prefill_s", "decode_s", "span_s"}``
    (span = journal timestamp extent, floored at total busy time)."""
    pre_s = dec_s = busy_s = 0.0
    t_lo = t_hi = None
    for e in events:
        if not isinstance(e, dict):
            continue
        t = e.get("t")
        if t is not None:
            t_lo = t if t_lo is None else min(t_lo, t)
            t_hi = t if t_hi is None else max(t_hi, t)
        if e.get("type") != "goodput_window":
            continue
        dur = float(e.get("dur_ms", 0.0)) / 1e3
        if dur <= 0:
            continue
        busy_s += dur
        kind = e.get("kind", "decode")
        if kind in _PREFILL_KINDS:
            pre_s += dur
        elif kind in _DECODE_KINDS:
            dec_s += dur
        else:  # oneshot / mixed: both phases in one window
            tokens = float(e.get("tokens", 0.0))
            dfrac = (float(e.get("decode_tokens", 0.0)) / tokens
                     if tokens > 0 else 0.5)
            dfrac = min(1.0, max(0.0, dfrac))
            dec_s += dur * dfrac
            pre_s += dur * (1.0 - dfrac)
    span = 0.0 if t_lo is None else float(t_hi) - float(t_lo)
    return {
        "prefill_s": round(pre_s, 9),
        "decode_s": round(dec_s, 9),
        "span_s": round(max(span, busy_s, 1e-9), 9),
    }


def pool_plan(events: Iterable[Dict], target_util: float = 0.6,
              span_s: Optional[float] = None, min_each: int = 1) -> Dict:
    """The offline answer to "how many prefill vs decode replicas does
    this trace need?": split the journal's chip time by phase
    (:func:`split_chip_time`), then size each tier with
    ``policy.pool_split``. Works on any flight journal — a live
    deployment's, or a ``simulate()`` run's synthetic one, which is the
    capacity-planning loop: record once, re-simulate the load shape
    you expect, read the split. ``span_s`` overrides the journal's
    timestamp extent (e.g. the wall duration a trace was recorded
    over). Returns the split inputs plus the sized plan."""
    split = split_chip_time(events)
    span = float(span_s) if span_s is not None else split["span_s"]
    plan = policy.pool_split(
        split["prefill_s"], split["decode_s"], span,
        target_util=target_util, min_each=min_each,
    )
    return {**split, "span_s": round(span, 9),
            "target_util": float(target_util), **plan}


# ----------------------------------------------------------------------
# the top-level run
# ----------------------------------------------------------------------

def simulate(trace, engine: Optional[SimEngine] = None, retries: int = 1,
             **engine_kw) -> Dict:
    """Run a trace through a ``SimEngine`` under the lockstep driver and
    return the what-if result: the synthetic journal, per-request token
    streams, virtual/wall seconds + speedup, virtual decode steps/s, and
    the goodput report rendered from the synthetic journal by the SAME
    offline pipeline the live journals go through."""
    replay = _load_sibling("replay")
    eng = engine if engine is not None else SimEngine(**engine_kw)
    arrivals = trace["arrivals"] if isinstance(trace, dict) else list(trace)
    for a in arrivals:  # recorded generation lengths are the oracle
        if "n_out" in a and a.get("rid") is not None:
            eng.out_len.setdefault(a["rid"], int(a["n_out"]))
    drv = replay.LockstepDriver(eng, emit=eng.emit, retries=retries)
    t0 = time.perf_counter()
    results = drv.drive(trace)
    wall_s = max(time.perf_counter() - t0, 1e-9)
    state = _goodput.state_from_events(eng.journal)
    virtual_s = max(eng.t, 1e-12)
    return {
        "results": results,
        "errors": {rid: repr(e) for rid, e in drv.errors.items()},
        "journal": eng.journal,
        "virtual_s": round(virtual_s, 6),
        "wall_s": round(wall_s, 6),
        "speedup_x": round(virtual_s / wall_s, 2),
        "windows": eng.windows,
        "decode_steps": eng.decode_steps,
        "steps_per_s": round(eng.decode_steps / virtual_s, 4),
        "tokens_out": sum(len(v) for v in results.values()),
        "report": _goodput.render_report(state, eng.chip_hour_usd),
        # disaggregated sizing: how many prefill- vs decode-role replicas
        # this load needs at 60% target busy (re-plan at a different
        # target with pool_plan(result["journal"], target_util=...))
        "pool_plan": pool_plan(eng.journal),
        # per-tenant cost split (tracegen traces carry tenant mixes): the
        # SAME renderer /debug/tenants and flightview --tenants use, so
        # "which tenant pays for the next replica" is answerable offline
        "tenant_report": _tenants.render_report(
            _tenants.state_from_events(eng.journal), eng.chip_hour_usd
        ),
    }
