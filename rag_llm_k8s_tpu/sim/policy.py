"""The continuous scheduler's decision core, as pure functions.

Every *decision* the continuous engine makes that is not device work —
block-allocation arithmetic, admission verdicts and headroom, prefill
grouping, sync-window growth planning, mixed-window budget splits,
preemption victim ordering, resubmit folding — lives here, and
``engine/continuous.py`` (plus ``engine/kv_pool.py``) delegates to these
functions on the live path. That seam is what makes the journal-replay
harness honest: ``sim/replay.py`` re-drives a recorded trace and
``sim/simulator.py`` steps a virtual engine through the SAME arithmetic,
so a simulated admission or preemption is the one the real scheduler
would have made, not a parallel reimplementation that drifts.

Import discipline: stdlib-only, no package-internal imports — this file
is loaded by path on hosts with no jax (flightview, capacity-planning
scripts); ragcheck's SIM-PURITY rule pins it. Sibling sim modules load
each other through ``load_sibling`` for the same reason.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


def load_sibling(name: str):
    """Load a sibling module of this package by FILE PATH (no package
    import, so no package ``__init__`` side effects and no jax) —
    ``load_sibling("replay")`` works on a bare-stdlib host. Relative
    paths reach outside the package too: ``load_sibling("../obs/goodput")``
    is how the simulator prices windows with the ledger's roofline."""
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.normpath(os.path.join(here, name + ".py"))
    modname = "_rag_sim_" + os.path.basename(name)
    spec = importlib.util.spec_from_file_location(modname, path)
    if spec is None or spec.loader is None:  # pragma: no cover - bad path
        raise ImportError(f"cannot load sibling module {name!r} from {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ----------------------------------------------------------------------
# block arithmetic (mirrors engine/kv_pool.py, which delegates here)
# ----------------------------------------------------------------------

def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks covering ``tokens`` KV positions (ceil; 0 for 0)."""
    return max(0, -(-int(tokens) // int(block_size)))


def admission_blocks(prompt_len: int, block_size: int) -> int:
    """Admission-time block cost of a prompt (an empty prompt still
    admits one BOS-like token, hence the floor at 1)."""
    return blocks_for(max(int(prompt_len), 1), block_size)


def window_blocks(kv_ub: int, horizon: int, block_size: int,
                  max_blocks_per_row: int) -> int:
    """Total blocks a row must have mapped before a window that writes
    ``horizon`` new positions past ``kv_ub`` — capped at the row's table
    size (the executable clamps ``kv_ub`` the same way)."""
    return min(blocks_for(int(kv_ub) + int(horizon), block_size),
               int(max_blocks_per_row))


# ----------------------------------------------------------------------
# admission
# ----------------------------------------------------------------------

def admission_verdict(
    need: int, usable: int, interleave_on: bool, max_blocks_per_row: int
) -> Tuple[str, int]:
    """The pool-pressure admission decision, minus the stateful reclaim
    loop: returns ``("never", 0)`` when the prompt alone outsizes the
    whole pool, ``("ok", 0)`` when incremental (interleaved) admission
    needs no up-front reservation, else ``("check", want)`` — the caller
    must find ``want`` allocatable blocks (reclaiming re-buildable
    registrations if it has any). ``want`` carries the +1 headroom so the
    first decode window can open the next block without instantly
    preempting what admission just placed, capped at the row table size
    (a prompt that exactly fills a row needs no headroom at all)."""
    if need > usable:
        return "never", 0
    if interleave_on:
        return "ok", 0
    return "check", min(int(need) + 1, int(max_blocks_per_row))


def bucket_len(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n, clamping to the largest (the engine's
    prompt-shape ladder; mirrors utils/buckets.py, restated here so the
    decision core stays importable with zero package imports)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def clamp_max_new(max_new: int, bucket: int, max_seq_len: int) -> int:
    """Clamp a request's budget to the cache room past its bucket — the
    prompt is never cut to make room for generation."""
    return max(1, min(int(max_new), int(max_seq_len) - int(bucket)))


def admission_chunks(
    bucketed: Sequence[Tuple[int, int]], max_batch: int
) -> List[Tuple[int, List[int]]]:
    """Group prepared admissions into prefill chunks: same-bucket
    requests batch together (one forward each chunk), chunk sizes stay
    powers of two so the executable ladder needs no fresh warmups, and
    both bucket order and in-bucket order preserve arrival order.
    ``bucketed`` is ``(item_index, bucket)`` per request; returns
    ``(bucket, [item_index, ...])`` chunks in execution order."""
    by_bucket: Dict[int, List[int]] = {}
    for idx, s in bucketed:
        by_bucket.setdefault(int(s), []).append(idx)
    chunks: List[Tuple[int, List[int]]] = []
    for s, group in by_bucket.items():
        pos = 0
        while pos < len(group):
            n = 1
            while n * 2 <= min(len(group) - pos, int(max_batch)):
                n *= 2
            chunks.append((s, group[pos:pos + n]))
            pos += n
    return chunks


# ----------------------------------------------------------------------
# sync-window growth + preemption ordering
# ----------------------------------------------------------------------

def grow_shortfall(
    rows: Iterable[Tuple[int, int, int, int]],  # (admit_seq, row, kv_ub, have)
    default_horizon: int,
    horizon: Optional[Dict[int, int]],
    block_size: int,
    max_blocks_per_row: int,
) -> List[Tuple[int, int, int, int]]:
    """Which active rows must grow their block tables before the next
    window, ordered oldest-admission-first (the growth priority the
    preemption discipline inverts): ``(admit_seq, row, missing, have)``.
    ``horizon`` overrides the per-row token horizon (speculative verify
    windows write ragged lengths); rows absent from an explicit map
    default to 1 — they still advance their frontier by the correction
    token."""
    short: List[Tuple[int, int, int, int]] = []
    for admit_seq, row, kv_ub, have in rows:
        h = default_horizon if horizon is None else horizon.get(row, 1)
        need_total = window_blocks(kv_ub, h, block_size, max_blocks_per_row)
        if need_total > have:
            short.append((admit_seq, row, need_total - have, have))
    short.sort()
    return short


def reclaim_registration(
    prefix_keys: Iterable, tier_of: Dict, gen_of: Dict
):
    """Growth-pressure registration victim: the least valuable prefix
    registration — non-hot before hot (a warm chunk costs one re-scatter
    to bring back, a hot one a proven-shared re-stage), oldest
    registration generation first within a tier."""
    keys = list(prefix_keys)
    if not keys:
        return None
    return min(keys, key=lambda k: (tier_of.get(k, "hot") == "hot",
                                    gen_of.get(k, 0)))


def preempt_victim(
    active: Iterable[Tuple[int, int]]  # (admit_seq, row)
) -> Tuple[int, int]:
    """Pool-exhaustion preemption victim: the NEWEST-admitted active row
    (vLLM-style recompute preemption — its emitted tokens go back to the
    scheduler, which resubmits once blocks free). Returns the winning
    ``(admit_seq, row)``."""
    victims = sorted(active)
    return victims[-1]


# ----------------------------------------------------------------------
# mixed (unified ragged) window planning
# ----------------------------------------------------------------------

def plan_mixed_window(
    admissions: Sequence[Tuple[int, int, int]],  # (rid, prompt_len, progress)
    window_budget: int,
    n_decode: int,
    chunk_tokens: int,
) -> List[Tuple[int, int, int, bool]]:
    """Budget split for one unified ragged window: active decode lanes
    cost one token each; the remainder slices pending admissions FIFO
    (oldest first — the request closest to its first token wins the
    leftover), at most ``chunk_tokens`` per admission per window.
    Returns ``(rid, offset, take, final)`` slices in schedule order; the
    caller allocates each slice's blocks and stops at the first slice
    the pool cannot stage (pool pressure idles the YOUNGER admissions
    for the window — later slices are exactly the ones dropped)."""
    remaining = max(0, int(window_budget) - int(n_decode))
    sched: List[Tuple[int, int, int, bool]] = []
    for rid, prompt_len, progress in admissions:
        if remaining <= 0:
            break
        left = int(prompt_len) - int(progress)
        take = min(int(chunk_tokens), remaining, left)
        if take <= 0:
            continue
        final = progress + take >= prompt_len
        sched.append((rid, int(progress), take, final))
        remaining -= take
    return sched


# ----------------------------------------------------------------------
# disaggregated pool sizing
# ----------------------------------------------------------------------

def pool_split(
    prefill_chip_s: float,
    decode_chip_s: float,
    span_s: float,
    target_util: float = 0.6,
    min_each: int = 1,
) -> Dict[str, float]:
    """How many prefill-role vs decode-role replicas a recorded load
    needs: each category's chip-seconds over the trace span, divided by
    the per-replica busy budget ``span_s * target_util``, rounded up,
    floored at ``min_each`` (an empty decode tier strands every migration
    packet; an empty prefill tier admits nothing). Returns the counts
    plus ``prefill_util``/``decode_util`` — each tier's busy fraction AT
    the returned count, the sanity read that the plan is neither
    saturated nor idle. Pure arithmetic so the capacity question is
    answerable on a bare-stdlib host from a journal alone."""
    span = max(float(span_s), 1e-9)
    budget = span * min(1.0, max(1e-6, float(target_util)))
    floor = max(1, int(min_each))
    n_pre = max(floor, int(-(-float(prefill_chip_s) // budget)))
    n_dec = max(floor, int(-(-float(decode_chip_s) // budget)))
    return {
        "prefill": n_pre,
        "decode": n_dec,
        "prefill_util": round(float(prefill_chip_s) / (n_pre * span), 6),
        "decode_util": round(float(decode_chip_s) / (n_dec * span), 6),
    }


# ----------------------------------------------------------------------
# resubmission (reset recovery / pool-preemption resume)
# ----------------------------------------------------------------------

def resume_fits(prompt_len: int, n_emitted: int, max_bucket: int) -> bool:
    """Whether a preempted/reset request may resume from prompt+emitted:
    past the largest bucket, admission would left-truncate the context
    and the 'seamless continuation' would be conditioned on a different
    prompt — restarting from scratch is exact, resuming is not."""
    return n_emitted > 0 and prompt_len + n_emitted <= max_bucket
