"""Journal-replay harness: the continuous scheduler's decision core,
factored behind a narrow seam (ISSUE 17, docs/REPLAY.md).

Import discipline: every module in this package is stdlib-only and makes
NO package-internal imports — the same loaded-by-file-path contract
``obs/goodput.py`` and ``obs/shadow.py`` carry (mechanized by ragcheck's
SIM-PURITY rule). ``scripts/flightview.py`` and offline capacity-planning
scripts load these files by path on hosts with no jax installed; sibling
modules reach each other through ``policy.load_sibling``.

Module map:
    policy.py     the pure decision core (block arithmetic, admission
                  verdicts, window planning, preemption ordering) — the
                  single source engine/continuous.py delegates to
    replay.py     journal parsing (forward-compatible), decision-stream
                  extraction/diffing, and the deterministic lockstep
                  driver that re-drives a trace against a live engine
    simulator.py  pure-host scheduler simulator: steps the decision core
                  with modeled window times, emits a flight-schema journal
    tracegen.py   seeded synthetic trace generator (sessions, bursts,
                  hot-chunk skew, tenant mixes)
"""
