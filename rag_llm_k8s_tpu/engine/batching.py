"""Continuous request batching for the serving engine.

The reference serves strictly sequentially: a single-threaded Flask dev server
runs one ``model.generate`` at a time (/root/reference/llm/rag.py:204) — a
second concurrent user waits for the whole first generation. Here concurrent
requests coalesce into batched decodes (BASELINE.json config #5: "batched
concurrent /query requests"): a dispatcher thread drains the queue, groups
waiting requests up to the engine's batch cap, and runs them as ONE device
program — decode cost is dominated by weight reads from HBM, so a batch of 8
costs barely more than a batch of 1.

Requests submit from any thread and block on their own event; results fan
back out in submission order. Grouping respects ``max_new_tokens``/seed so
every request in a batch shares one executable.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from rag_llm_k8s_tpu.engine.engine import InferenceEngine

logger = logging.getLogger(__name__)


def _join_worker(worker: threading.Thread, counter, what: str, timeout: float = 5.0):
    """Join a scheduler/coalescer worker, loudly: a worker that outlives the
    join window (wedged in a device call) used to vanish in silence — the
    drains still unblock every caller, but the leak should be visible on a
    dashboard (``rag_scheduler_join_timeouts_total``) and in the logs."""
    worker.join(timeout=timeout)
    if worker.is_alive():
        logger.warning(
            "%s worker still alive after join(%gs); queued callers have "
            "been failed fast but the worker thread may be wedged",
            what, timeout,
        )
        if counter is not None:
            counter.inc()


@dataclass
class _Pending:
    prompt: List[int]
    max_new: Optional[int]
    seed: Optional[int]
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[List[int]] = None
    error: Optional[BaseException] = None
    t_enqueue: float = field(default_factory=time.monotonic)  # wait anchor


@dataclass
class _PendingItem:
    value: object
    done: threading.Event = field(default_factory=threading.Event)
    result: object = None
    error: Optional[BaseException] = None
    t_enqueue: float = field(default_factory=time.monotonic)  # wait anchor


class Coalescer:
    """Generic blocking coalescer: concurrent ``submit(x)`` calls are grouped
    and served by ONE ``batch_fn([x, ...])`` call on a worker thread.

    This is the serving fix for the *retrieval* stage: without it, N
    concurrent queries dispatch N separate fused embed+kNN device calls that
    serialize on the device queue (and, over a tunneled TPU, pay a
    device→host fetch each). Coalesced, the first query runs while the rest
    accumulate, and the entire remainder runs as one batched device call —
    the same continuous-batching effect the decode path already gets from
    :class:`BatchScheduler`, applied to embed+kNN.

    ``max_wait_ms`` can stay tiny (even 0): while the worker is busy with one
    batch, later arrivals queue up and form the next batch naturally.

    ``pending_hint`` (optional, settable after construction): a callable
    returning how many requests are currently in flight toward this stage.
    When set, the drain loop stops waiting as soon as every in-flight
    request has joined the batch — a solo query pays ~ the small
    ``hint_grace_ms`` instead of the full window, while a burst still
    coalesces fully. The grace exists because the hint counts only
    requests that have ENTERED the serving pipeline: a cold burst's
    stragglers may still be in HTTP parsing when the first request's
    batch forms, and trusting a hint of 1 instantly would re-create the
    batch-of-1 burst regression the window prevents. The window deadline
    stays the upper bound (a hinted request that errors before submitting
    just costs the old fixed wait).
    """

    def __init__(
        self, batch_fn, max_batch: int, max_wait_ms: float = 2.0, pending_hint=None,
        hint_grace_ms: float = 4.0,
    ):
        self.batch_fn = batch_fn
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.pending_hint = pending_hint
        self.hint_grace_ms = hint_grace_ms
        # optional obs Histogram (settable after construction, like
        # pending_hint): per-item enqueue→dispatch wait — the coalesce
        # window's real cost per request on a dashboard
        self.wait_histogram = None
        # optional obs Counter — shutdown join timeouts (see _join_worker)
        self.join_timeout_counter = None
        self._queue: "queue.Queue[_PendingItem]" = queue.Queue()
        self._stop = threading.Event()
        self._lifecycle_lock = threading.Lock()
        self._worker = threading.Thread(target=self._run, daemon=True, name="coalescer")
        self._worker.start()

    def submit(self, value, timeout: Optional[float] = None):
        item = _PendingItem(value=value)
        with self._lifecycle_lock:  # stop-check + enqueue must be atomic
            if self._stop.is_set():
                raise RuntimeError("coalescer is shut down")
            self._queue.put(item)
        if not item.done.wait(timeout):
            raise TimeoutError("coalesced call timed out")
        if item.error is not None:
            raise item.error
        return item.result

    def shutdown(self):
        self._stop.set()
        self._queue.put(None)
        _join_worker(self._worker, self.join_timeout_counter, "coalescer")

    def _run(self):
        try:
            while not self._stop.is_set():
                first = self._queue.get()
                if first is None:
                    continue
                batch = [first]
                # absolute deadline: the window bounds the FIRST item's wait;
                # a per-get timeout would reset on every arrival and stretch
                # the worst case to (max_batch-1) x window under trickle load
                now = time.monotonic()
                deadline = now + self.max_wait_ms / 1e3
                hint_from = now + min(self.hint_grace_ms, self.max_wait_ms) / 1e3
                while len(batch) < self.max_batch:
                    hint = self.pending_hint
                    now = time.monotonic()
                    if (
                        hint is not None and now >= hint_from
                        and len(batch) >= hint()
                    ):
                        # everything in flight toward this stage is already
                        # aboard — waiting longer can only add latency. The
                        # grace window has passed, so a cold burst's
                        # stragglers have had time to register themselves.
                        break
                    # with a hint, sleep only until the grace boundary first
                    # — a timeout there re-evaluates the hint, not the batch
                    wait_until = (
                        hint_from if hint is not None and now < hint_from
                        else deadline
                    )
                    remaining = wait_until - now
                    try:
                        # past the deadline, still DRAIN whatever is already
                        # queued (zero wait) — with max_wait_ms=0 this is
                        # the whole contract: items that accumulated while
                        # the worker was busy form one batch
                        nxt = (
                            self._queue.get(timeout=remaining)
                            if remaining > 0 else self._queue.get_nowait()
                        )
                    except queue.Empty:
                        if wait_until < deadline:
                            continue  # grace elapsed; re-check the hint
                        break
                    if nxt is None:
                        break
                    batch.append(nxt)
                hist = self.wait_histogram
                if hist is not None:
                    now = time.monotonic()
                    for b in batch:
                        hist.observe(now - b.t_enqueue)
                try:
                    results = self.batch_fn([b.value for b in batch])
                    if len(results) != len(batch):
                        raise RuntimeError(
                            f"batch_fn returned {len(results)} results for "
                            f"{len(batch)} items"
                        )
                    for b, r in zip(batch, results):
                        b.result = r
                except BaseException as e:  # noqa: BLE001 — deliver to all waiters
                    for b in batch:
                        b.error = e
                finally:
                    for b in batch:
                        b.done.set()
        finally:
            # close the door, then fail everything still queued so no caller
            # blocks forever on a dead worker (submits use timeout=None)
            self._stop.set()
            err = RuntimeError("coalescer is shut down")
            with self._lifecycle_lock:
                while True:
                    try:
                        queued = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if queued is not None:
                        queued.error = err
                        queued.done.set()


class BatchScheduler:
    def __init__(
        self,
        engine: InferenceEngine,
        max_wait_ms: float = 5.0,
        pending_hint=None,  # see Coalescer.pending_hint — same contract
    ):
        self.engine = engine
        self.max_wait_ms = max_wait_ms
        self.pending_hint = pending_hint
        # optional obs Histogram — see Coalescer.wait_histogram
        self.wait_histogram = None
        # optional obs Counter — shutdown join timeouts (see _join_worker)
        self.join_timeout_counter = None
        # size of the batch currently inside engine.generate (0 between
        # dispatches) — the rag_batch_occupancy gauge reads this; plain
        # int assignment, so no lock needed for the scrape-time read
        self.in_flight = 0
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        # serializes submit's stop-check+enqueue against shutdown's final
        # drain — without it an item can land in the queue after the drain
        # and block its (timeout=None) caller forever
        self._lifecycle_lock = threading.Lock()
        self._worker = threading.Thread(target=self._run, daemon=True, name="batch-scheduler")
        self._worker.start()

    # ------------------------------------------------------------------
    def submit(
        self,
        prompt: List[int],
        max_new_tokens: Optional[int] = None,
        seed: Optional[int] = None,
        timeout: Optional[float] = None,
        deadline=None,  # Optional[resilience.Deadline]
        info: Optional[dict] = None,  # accepted for scheduler-API parity;
        # only the continuous scheduler has per-request engine facts to fill
        tenant: Optional[str] = None,  # parity again: the continuous path
        # stamps tenant into the flight journal / goodput ledger; one-shot
        # batches carry no per-request ledger rows to attribute
    ) -> List[int]:
        """Blocking: enqueue and wait for this prompt's continuation.

        A ``deadline`` bounds the wait (the caller's remaining budget); the
        batch itself cannot be cancelled mid-generate — one-shot generation
        is a single device call — so expiry surfaces as the caller's
        :class:`DeadlineExceeded` while the batch completes for its
        surviving members."""
        if timeout is None and deadline is not None:
            timeout = deadline.wait_timeout()
        item = _Pending(prompt=list(prompt), max_new=max_new_tokens, seed=seed)
        with self._lifecycle_lock:  # stop-check + enqueue must be atomic
            if self._stop.is_set():
                raise RuntimeError("scheduler is shut down")
            self._queue.put(item)
        if not item.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if item.error is not None:
            raise item.error
        return item.result

    def shutdown(self):
        self._stop.set()
        self._queue.put(None)  # wake the worker
        _join_worker(self._worker, self.join_timeout_counter, "batch-scheduler")

    # ------------------------------------------------------------------
    def _run(self):
        carry: Optional[_Pending] = None
        try:
            carry = self._run_loop()
        finally:
            # the worker is exiting for WHATEVER reason (shutdown() or an
            # unguarded exception): close the door first, or submits racing
            # this drain would enqueue after it and block forever
            self._stop.set()
            # fail everything still queued or carried so no caller blocks
            # forever on a scheduler that has stopped (the server submits
            # with timeout=None)
            err = RuntimeError("scheduler is shut down")
            leftovers = [carry] if carry is not None else []
            with self._lifecycle_lock:
                while True:
                    try:
                        queued = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if queued is not None:
                        leftovers.append(queued)
            for it in leftovers:
                it.error = err
                it.done.set()

    def _run_loop(self) -> Optional[_Pending]:
        """Returns the un-acked in-hand item (if any) when stopping."""
        carry: Optional[_Pending] = None
        while not self._stop.is_set():
            first = carry if carry is not None else self._queue.get()
            carry = None
            if first is None:
                continue
            batch = [first]
            cap = self.engine.engine_config.max_batch_size
            # drain compatible requests within the coalescing window — an
            # ABSOLUTE deadline (a per-get timeout resets on every arrival:
            # worst case (cap-1) x window under trickle load)
            deadline = time.monotonic() + self.max_wait_ms / 1e3
            while len(batch) < cap:
                hint = self.pending_hint
                if hint is not None and len(batch) >= hint():
                    # every in-flight request is already aboard (solo query:
                    # immediately) — don't burn the window waiting for nobody
                    break
                remaining = deadline - time.monotonic()
                try:
                    # past the deadline, still drain already-queued items
                    # (zero wait) — they accumulated while this worker ran
                    nxt = (
                        self._queue.get(timeout=remaining)
                        if remaining > 0 else self._queue.get_nowait()
                    )
                except queue.Empty:
                    break
                if nxt is None:
                    break
                if nxt.max_new == first.max_new and nxt.seed == first.seed:
                    batch.append(nxt)
                else:
                    # different executable: lead the NEXT round (a tail
                    # re-queue would reorder it behind later arrivals and
                    # could starve it under sustained mixed load)
                    carry = nxt
                    break
            hist = self.wait_histogram
            if hist is not None:
                now = time.monotonic()
                for b in batch:
                    hist.observe(now - b.t_enqueue)
            self.in_flight = len(batch)
            try:
                outs = self.engine.generate(
                    [b.prompt for b in batch],
                    max_new_tokens=first.max_new,
                    seed=first.seed,
                )
                for b, out in zip(batch, outs):
                    b.result = out
            except BaseException as e:  # noqa: BLE001 — deliver to all waiters
                for b in batch:
                    b.error = e
            finally:
                self.in_flight = 0
                for b in batch:
                    b.done.set()
        return carry
