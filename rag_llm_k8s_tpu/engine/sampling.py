"""Token sampling: greedy and temperature/top-p (nucleus).

Parity target: the reference calls ``model.generate(max_new_tokens=150,
temperature=0.7, top_p=0.9)`` (/root/reference/llm/rag.py:172), with sampling
enabled by the model's bundled generation_config. The nucleus rule here matches
HF's ``TopPLogitsWarper``: keep the smallest descending-probability prefix
whose cumulative mass reaches ``top_p`` (always at least one token).

Everything is shape-static and branch-free — safe under jit/scan on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from rag_llm_k8s_tpu.core.config import SamplingConfig

NEG_INF = -1e9


def top_p_filter_sort(logits: jax.Array, top_p: float) -> jax.Array:
    """Reference nucleus filter via a full descending sort (HF's
    ``TopPLogitsWarper`` shape). Kept as the oracle for the bisection
    implementation below — a [B, 128k] fp32 sort costs milliseconds per
    decode step on TPU, so serving never runs this."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # token i is kept iff the mass strictly before it is < top_p
    keep_sorted = (cum - probs) < top_p
    # threshold = smallest kept logit; everything below it is filtered
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= threshold, logits, NEG_INF)


def top_p_filter(logits: jax.Array, top_p: float, iters: int = 30) -> jax.Array:
    """Mask logits outside the nucleus. ``logits: [..., V]`` (any batch dims).

    Sort-free: bisect the probability threshold ``t`` such that the mass of
    ``{p_i > t}`` still reaches ``top_p`` — ``iters`` fused linear passes
    over the row instead of an O(V log^2 V) bitonic sort (the sort was a
    material slice of the 1B decode step at the 128256 vocab; see
    docs/DECODE_PERF.md). After 30 halvings the bracket has width
    ``pmax * 2^-30``: the kept set equals the sort-based oracle's except
    (a) boundary TIES, where this keeps every tied token (HF's sort keeps
    an arbitrary subset), and (b) tokens whose probability lies within the
    final bracket of the true threshold — at most ``pmax * 1e-9`` of extra
    mass per such token, distributionally negligible but not bit-identical
    (the parity test compares within that band).
    """
    probs = jax.nn.softmax(logits, axis=-1)
    pmax = jnp.max(probs, axis=-1, keepdims=True)

    def body(_, bracket):
        lo, hi = bracket
        mid = (lo + hi) * 0.5
        mass = jnp.sum(jnp.where(probs > mid, probs, 0.0), axis=-1, keepdims=True)
        ge = mass >= top_p
        return jnp.where(ge, mid, lo), jnp.where(ge, hi, mid)

    lo = jnp.zeros_like(pmax)
    lo, _ = jax.lax.fori_loop(0, iters, body, (lo, pmax))
    # keep {p > lo}; pmax is always in (argmax survives even at top_p ~ 0)
    keep = (probs > lo) | (probs >= pmax)
    return jnp.where(keep, logits, NEG_INF)


def _prepared_logits(logits: jax.Array, sampling: SamplingConfig):
    """Shared pipeline: ``None`` means greedy (argmax), otherwise the
    temperature-scaled, nucleus-filtered logits to draw from."""
    if not sampling.do_sample or sampling.temperature <= 0.0:
        return None
    scaled = logits / sampling.temperature
    if sampling.top_p < 1.0:
        scaled = top_p_filter(scaled, sampling.top_p)
    return scaled


def sample_token(
    rng: jax.Array,
    logits: jax.Array,  # [B, V] fp32
    sampling: SamplingConfig,
) -> jax.Array:
    """One sampling step -> token ids ``[B]`` (int32)."""
    scaled = _prepared_logits(logits, sampling)
    if scaled is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)


def sample_token_per_row(
    keys: jax.Array,  # [B, 2] uint32 — one PRNGKey per row
    logits: jax.Array,  # [B, V] fp32
    sampling: SamplingConfig,
) -> jax.Array:
    """Per-row-keyed sampling step -> token ids ``[B]`` (int32).

    Continuous batching needs independent randomness per slot: rows carry
    their own keys so a request's draws don't depend on its batchmates."""
    scaled = _prepared_logits(logits, sampling)
    if scaled is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.vmap(
        lambda k, row: jax.random.categorical(k, row, axis=-1)
    )(keys, scaled).astype(jnp.int32)
