"""Token sampling: greedy and temperature/top-p (nucleus).

Parity target: the reference calls ``model.generate(max_new_tokens=150,
temperature=0.7, top_p=0.9)`` (/root/reference/llm/rag.py:172), with sampling
enabled by the model's bundled generation_config. The nucleus rule here matches
HF's ``TopPLogitsWarper``: keep the smallest descending-probability prefix
whose cumulative mass reaches ``top_p`` (always at least one token).

Everything is shape-static and branch-free — safe under jit/scan on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from rag_llm_k8s_tpu.core.config import SamplingConfig

NEG_INF = -1e9


def top_p_filter_sort(logits: jax.Array, top_p: float) -> jax.Array:
    """Reference nucleus filter via a full descending sort (HF's
    ``TopPLogitsWarper`` shape). Kept as the oracle for the bisection
    implementation below — a [B, 128k] fp32 sort costs milliseconds per
    decode step on TPU, so serving never runs this."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # token i is kept iff the mass strictly before it is < top_p
    keep_sorted = (cum - probs) < top_p
    # threshold = smallest kept logit; everything below it is filtered
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= threshold, logits, NEG_INF)


def top_p_filter(logits: jax.Array, top_p: float, iters: int = 30) -> jax.Array:
    """Mask logits outside the nucleus. ``logits: [..., V]`` (any batch dims).

    Sort-free: bisect the probability threshold ``t`` such that the mass of
    ``{p_i > t}`` still reaches ``top_p`` — ``iters`` fused linear passes
    over the row instead of an O(V log^2 V) bitonic sort (the sort was a
    material slice of the 1B decode step at the 128256 vocab; see
    docs/DECODE_PERF.md). After 30 halvings the bracket has width
    ``pmax * 2^-30``: the kept set equals the sort-based oracle's except
    (a) boundary TIES, where this keeps every tied token (HF's sort keeps
    an arbitrary subset), and (b) tokens whose probability lies within the
    final bracket of the true threshold — at most ``pmax * 1e-9`` of extra
    mass per such token, distributionally negligible but not bit-identical
    (the parity test compares within that band).
    """
    probs = jax.nn.softmax(logits, axis=-1)
    pmax = jnp.max(probs, axis=-1, keepdims=True)

    def body(_, bracket):
        lo, hi = bracket
        mid = (lo + hi) * 0.5
        mass = jnp.sum(jnp.where(probs > mid, probs, 0.0), axis=-1, keepdims=True)
        ge = mass >= top_p
        return jnp.where(ge, mid, lo), jnp.where(ge, hi, mid)

    lo = jnp.zeros_like(pmax)
    lo, _ = jax.lax.fori_loop(0, iters, body, (lo, pmax))
    # keep {p > lo}; pmax is always in (argmax survives even at top_p ~ 0)
    keep = (probs > lo) | (probs >= pmax)
    return jnp.where(keep, logits, NEG_INF)


def _prepared_logits(logits: jax.Array, sampling: SamplingConfig):
    """Shared pipeline: ``None`` means greedy (argmax), otherwise the
    temperature-scaled, nucleus-filtered logits to draw from."""
    if not sampling.do_sample or sampling.temperature <= 0.0:
        return None
    scaled = logits / sampling.temperature
    if sampling.top_p < 1.0:
        scaled = top_p_filter(scaled, sampling.top_p)
    return scaled


def sample_token(
    rng: jax.Array,
    logits: jax.Array,  # [B, V] fp32
    sampling: SamplingConfig,
) -> jax.Array:
    """One sampling step -> token ids ``[B]`` (int32)."""
    scaled = _prepared_logits(logits, sampling)
    if scaled is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)


def sample_token_per_row(
    keys: jax.Array,  # [B, 2] uint32 — one PRNGKey per row
    logits: jax.Array,  # [B, V] fp32
    sampling: SamplingConfig,
) -> jax.Array:
    """Per-row-keyed sampling step -> token ids ``[B]`` (int32).

    Continuous batching needs independent randomness per slot: rows carry
    their own keys so a request's draws don't depend on its batchmates."""
    scaled = _prepared_logits(logits, sampling)
    if scaled is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.vmap(
        lambda k, row: jax.random.categorical(k, row, axis=-1)
    )(keys, scaled).astype(jnp.int32)


# ---------------------------------------------------------------------------
# speculative verify-step acceptance (paged draft-and-verify)
# ---------------------------------------------------------------------------


def sample_targets_per_row(
    keys: jax.Array,  # [B, S, 2] uint32 — plane j's key = fold(row key, pos)
    logits: jax.Array,  # [B, S, V] fp32 — one plane per fed token
    sampling: SamplingConfig,
) -> jax.Array:
    """The verify step's TARGET tokens ``[B, S]``: what the vanilla
    continuous step would have sampled at each plane. Plane ``j``'s draw
    uses exactly the key the step loop would have folded for that token's
    position, so greedy (argmax) AND seeded sampling verify steps emit the
    byte-identical stream — speculative acceptance below is "does the
    draft equal this target", one rule for both modes (the one-shot
    engine's rejection-sampling rule is only needed for UNKEYED draws;
    the continuous engine's draws are (seed, position)-deterministic, so
    target matching is exact, not just distribution-preserving)."""
    scaled = _prepared_logits(logits, sampling)
    if scaled is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    B, S, V = logits.shape
    flat = jax.vmap(
        lambda k, row: jax.random.categorical(k, row, axis=-1)
    )(keys.reshape(B * S, 2), scaled.reshape(B * S, V))
    return flat.reshape(B, S).astype(jnp.int32)


def accept_drafts(
    drafts: jax.Array,  # [B, K] int32 — proposed continuations
    targets: jax.Array,  # [B, K+1] int32 — the model's own tokens per plane
    n_drafts: jax.Array,  # [B] int32 — real drafts per row (<= K)
):
    """Per-row longest-prefix acceptance: row ``b`` accepts drafts while
    they equal the model's targets (and stay within its own ``n_drafts``),
    then emits the target at the first mismatch position — the correction
    (or, on full acceptance, the bonus target from the last plane). Returns
    ``(m, emitted)``: ``m [B]`` accepted prefix lengths and ``emitted
    [B, K+1]`` where planes ``0..m`` are the row's emitted tokens (plane
    ``m`` is the correction/bonus; planes past ``m`` are junk the host
    never reads — it drains exactly ``m + 1`` per row). Shape-static and
    branch-free, safe inside the verify executable."""
    B, K = drafts.shape
    i32 = jnp.int32
    j = jnp.arange(K, dtype=i32)[None, :]
    ok = (drafts == targets[:, :K]) & (j < n_drafts[:, None])
    acc = jnp.cumprod(ok.astype(i32), axis=1)
    m = jnp.sum(acc, axis=1)  # [B] in [0, n_drafts]
    jj = jnp.arange(K + 1, dtype=i32)[None, :]
    ext = jnp.concatenate([drafts, jnp.zeros((B, 1), i32)], axis=1)
    corr = jnp.take_along_axis(targets, m[:, None], axis=1)  # [B, 1]
    emitted = jnp.where(jj == m[:, None], corr, ext)
    return m, emitted
