"""Hotness-aware KV tiering primitives (HA-RAG).

The prefix cache and block pool treat every cached chunk's KV identically:
all of it bf16 (or the engine's native kv dtype), all of it in HBM. That
caps the effective cache at whatever the HBM budget holds — fine for a demo
corpus, nowhere near the hot set of a million-user document base. HA-RAG
(PAPERS.md) closes the gap with hotness-driven mixed precision and data
placement; this module supplies the host-side primitives the cache layers
build tiering from:

- :class:`HotnessTracker` — an exponentially-decayed hit-frequency score
  per chunk key, fed by prefix-cache resolve hits, lookahead joins, and
  pool prestage registrations. The score is the ONE signal every tier
  decision reads: hot chunks stay in their native dtype, warm chunks
  quantize to int8 in place, cold chunks spill to host RAM.
- :class:`HostSpillStore` — a byte-budgeted host-RAM store of spilled
  chunk planes (numpy copies of the device arrays). A spilled chunk costs
  ZERO HBM and swaps back in with one ``device_put`` — orders of magnitude
  cheaper than re-prefilling it (swap-in is bandwidth; prefill is flops
  over every layer), and the swap-in rides the lookahead pipeline so it
  overlaps the previous request's decode instead of stalling admission.
- ``quantize_planes`` / ``dequantize_planes`` — the warm tier's in-place
  int8 conversion of a cached ``(k, v)`` plane pair (the same per-(token,
  kv-head) symmetric scales the ``_q8`` attention kernels dequantize at,
  via :func:`ops.attention.quantize_kv`), with NO re-prefill: the bytes
  halve, the dequant error is bounded at max|x|/254 per element, and the
  pinned-tolerance tests hold decoded streams to it.

Everything here is host bookkeeping plus tiny jit'd conversions; the tier
POLICY (when to demote, what a transition must preserve) lives with the
caches that own the entries (engine/prefix_cache.py, engine/continuous.py).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rag_llm_k8s_tpu.obs import flight

__all__ = [
    "TIERS",
    "HotnessTracker",
    "HostSpillStore",
    "quantize_planes",
    "dequantize_planes",
]

TIERS = ("hot", "warm", "cold")


class HotnessTracker:
    """Decayed hit-frequency per chunk key.

    ``touch(key, w)`` adds ``w`` to the key's score; scores decay
    exponentially with the configured half-life, evaluated lazily at read
    time (no decay thread — a score is ``raw * 2^(-age/half_life)``).
    Thread-safe; the clock is injectable so tests pin exact decay math.
    """

    def __init__(self, half_life_s: float = 60.0, clock=time.monotonic):
        if half_life_s <= 0:
            raise ValueError(f"half_life_s={half_life_s}: expected > 0")
        self.half_life_s = float(half_life_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._scores: Dict[object, Tuple[float, float]] = {}  # key -> (raw, t)

    def _decayed(self, raw: float, t: float, now: float) -> float:
        return raw * 2.0 ** (-(now - t) / self.half_life_s)

    def touch(self, key, weight: float = 1.0) -> float:
        """Record a use; returns the key's new (decayed) score."""
        now = self._clock()
        with self._lock:
            raw, t = self._scores.get(key, (0.0, now))
            score = self._decayed(raw, t, now) + float(weight)
            self._scores[key] = (score, now)
            return score

    def score(self, key) -> float:
        now = self._clock()
        with self._lock:
            entry = self._scores.get(key)
            if entry is None:
                return 0.0
            return self._decayed(entry[0], entry[1], now)

    def forget(self, key) -> None:
        with self._lock:
            self._scores.pop(key, None)

    def prune(self, floor: float = 1e-3) -> int:
        """Drop keys whose decayed score fell under ``floor`` (the tracker
        must not grow with every chunk ever seen). Returns pruned count."""
        now = self._clock()
        with self._lock:
            dead = [
                k for k, (raw, t) in self._scores.items()
                if self._decayed(raw, t, now) < floor
            ]
            for k in dead:
                del self._scores[k]
            return len(dead)

    def __len__(self) -> int:
        with self._lock:
            return len(self._scores)


class HostSpillStore:
    """Byte-budgeted host-RAM store of cold-spilled chunk planes.

    Values are tuples of numpy arrays (host copies of the device planes)
    plus opaque metadata the owning cache round-trips. Inserts past the
    budget evict oldest-first — a cold chunk falling off the host store
    simply recomputes on its next miss, exactly like a never-cached chunk.
    Thread-safe (the cache calls under its own lock too, but scrapes and
    tests read concurrently).
    """

    def __init__(self, budget_mb: int = 1024):
        if budget_mb < 1:
            raise ValueError(f"budget_mb={budget_mb}: expected >= 1")
        self.budget_bytes = int(budget_mb) * (1 << 20)
        self._lock = threading.Lock()
        self._data: "Dict[object, Tuple[Tuple[np.ndarray, ...], dict, int]]" = {}
        self._order: list = []  # insertion order (oldest first)
        self.bytes = 0
        # cumulative counters (tier stats / bench)
        self.spills = 0
        self.evictions = 0

    def put(self, key, planes: Tuple, meta: Optional[dict] = None) -> int:
        """Store host copies of ``planes``; returns bytes now held for the
        key. Oldest entries evict until the budget holds (the entry being
        inserted is never its own victim)."""
        host = tuple(np.asarray(p) for p in planes)
        nbytes = int(sum(p.nbytes for p in host))
        evicted = 0
        with self._lock:
            self._drop_locked(key)
            self._data[key] = (host, dict(meta or {}), nbytes)
            self._order.append(key)
            self.bytes += nbytes
            self.spills += 1
            while self.bytes > self.budget_bytes and len(self._order) > 1:
                victim = self._order[0]
                if victim == key:
                    break
                self._drop_locked(victim)
                self.evictions += 1
                evicted += 1
        if evicted:
            # a budget-evicted cold chunk can never swap back in — its
            # next use is a plain recompute; the journal names the moment
            flight.emit("host_spill_evict", evicted=evicted, bytes=self.bytes)
        return nbytes

    def get(self, key) -> Optional[Tuple[Tuple[np.ndarray, ...], dict]]:
        with self._lock:
            entry = self._data.get(key)
            if entry is None:
                return None
            return entry[0], dict(entry[1])

    def _drop_locked(self, key) -> bool:
        entry = self._data.pop(key, None)
        if entry is None:
            return False
        try:
            self._order.remove(key)
        except ValueError:
            pass
        self.bytes -= entry[2]
        return True

    def drop(self, key) -> bool:
        """Release one spilled entry's host buffer."""
        with self._lock:
            return self._drop_locked(key)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self._order.clear()
            self.bytes = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._data

    def manifest(self) -> list:
        """The store's inventory, oldest first: ``{key, nbytes, meta}``
        per spilled entry. Host buffers die with the process, but the
        manifest's identity (which keys were cold-but-kept, how big)
        feeds the prefix cache's warmth manifest (ISSUE 19): the chunks
        a crashed replica had spilled are exactly the ones a warm
        restart re-stages first."""
        with self._lock:
            return [
                {"key": key, "nbytes": self._data[key][2],
                 "meta": dict(self._data[key][1])}
                for key in self._order
            ]


@jax.jit
def _quantize_pair(k, v):
    from rag_llm_k8s_tpu.ops.attention import quantize_kv

    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    return kq, vq, ks, vs


@functools.partial(jax.jit, static_argnames=("dtype",))
def _dequantize_pair(kq, vq, ks, vs, *, dtype):
    k = (kq.astype(jnp.float32) * ks[..., None]).astype(dtype)
    v = (vq.astype(jnp.float32) * vs[..., None]).astype(dtype)
    return k, v


def quantize_planes(planes: Tuple) -> Optional[Tuple]:
    """Warm-tier conversion of a cached KV plane tuple: ``(k, v)`` native
    payloads become ``(k_q, v_q, k_scale, v_scale)`` — int8 payloads with
    one fp32 symmetric scale per (token, kv-head) vector, the exact layout
    every ``_q8`` kernel dequantizes at. NO re-prefill happens: the bytes
    already in HBM are converted in place (old planes freed by the caller
    dropping its reference). Returns None when the tuple is already
    quantized (an int8-KV engine's entries — warm is a tier label there,
    not a byte change)."""
    if len(planes) != 2:
        return None  # already (payload, payload, scale, scale)
    k, v = planes
    if getattr(k, "dtype", None) == jnp.int8:
        return None
    return tuple(_quantize_pair(k, v))


def dequantize_planes(planes: Tuple, dtype) -> Tuple:
    """Inverse of :func:`quantize_planes`: rebuild ``(k, v)`` in ``dtype``
    from a warm entry's int8 payloads + scales (the splice/scatter paths
    consume native-dtype planes). The int8 round trip is the warm tier's
    bounded quality cost — max|x|/254 per element, pinned by the
    forced-demotion tolerance tests."""
    if len(planes) == 2:
        return planes
    kq, vq, ks, vs = planes
    return tuple(_dequantize_pair(kq, vq, ks, vs, dtype=jnp.dtype(dtype)))
