"""The inference engine: bucketed prefill + while-loop decode, compiled once
per (batch, bucket) shape.

Replaces the reference's per-request ``model.generate`` on CPU torch
(/root/reference/llm/rag.py:172). Design, TPU-first:

- **Static shapes, bucketed prompts**: a prompt pads LEFT to the next bucket
  (``EngineConfig.prompt_buckets``); XLA compiles one executable per
  (batch_bucket, prompt_bucket, max_new) triple and reuses it for every
  request — no per-request recompiles, no dynamic shapes.
- **Left padding** keeps every sequence's write frontier at the same cache
  index, so cache appends stay ``dynamic_update_slice`` (survey §7 hard part
  (b): KV layout under pjit without per-request recompiles).
- **The whole generate call is ONE compiled function**: prefill (last-token
  logits only), the ``lax.while_loop`` over decode steps, sampling, and EOS
  tracking all live on device; the host sees only final token ids. With
  params placed via NamedSharding, XLA propagates TP shardings through the
  loop and inserts ICI collectives.
- **AOT compilation**: executables are built with ``jit(...).lower().compile()``
  from abstract shapes, so ``warmup()`` pays compile time only — no throwaway
  generations (readiness gating for the server).
- **Early exit**: the while_loop stops when every row has emitted EOS —
  short answers don't pay for ``max_new_tokens`` steps (the reference always
  runs the full HF sequential loop per request).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.core.mesh import MeshContext
from rag_llm_k8s_tpu.engine.sampling import NEG_INF, _prepared_logits, sample_token
from rag_llm_k8s_tpu.models.llama import (
    KVCache,
    LlamaModel,
    make_kv_cache,
    mask_window,
)
from rag_llm_k8s_tpu.obs import flight
from rag_llm_k8s_tpu.obs import goodput as obs_goodput
from rag_llm_k8s_tpu.obs import metrics as obs_metrics
from rag_llm_k8s_tpu.resilience import faults
from rag_llm_k8s_tpu.utils.buckets import bucket_len, next_pow2

logger = logging.getLogger(__name__)


@jax.jit
def _splice_prefix_planes(dst, block, offset):
    """Write a segment KV block into a prefix buffer at slot ``offset``.

    Both are plane tuples — payloads ``[L, 1, K, T, hd]`` and (int8-KV)
    scale planes ``[L, 1, K, T]``; the slot axis is 3 in both layouts.
    jit-cached per (buffer, block-bucket) shape pair, so splicing stays a
    bounded set of tiny executables regardless of how many distinct prefixes
    ever assemble.
    """
    out = []
    for c, b in zip(dst, block):
        starts = (0, 0, 0, offset) + ((0,) if c.ndim == 5 else ())
        out.append(jax.lax.dynamic_update_slice(c, b.astype(c.dtype), starts))
    return tuple(out)


def _isin(tokens: jax.Array, ids: Tuple[int, ...]) -> jax.Array:
    hit = jnp.zeros(tokens.shape, dtype=bool)
    for i in ids:
        hit = hit | (tokens == i)
    return hit


def param_avals(params):
    """Abstract (shape, dtype, sharding) tree for AOT ``.lower()`` calls —
    shared by the one-shot and continuous engines."""
    return jax.tree.map(
        lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=leaf.sharding)
        if isinstance(leaf, jax.Array)
        else jax.ShapeDtypeStruct(np.shape(leaf), np.asarray(leaf).dtype),
        params,
    )


def maybe_fuse_params(params, engine_config: EngineConfig, mesh):
    """Fuse q/k/v and gate/up projection weights once at engine construction
    when the config allows it and tp == 1 (the fused concat layout cannot be
    tp-sharded — see ``models.llama.fuse_llama_params``). Returns
    ``(params, fused?)``; already-fused or sharded trees pass through."""
    from rag_llm_k8s_tpu.models.llama import fuse_llama_params

    tp = mesh.tp if mesh is not None else 1
    attn = params.get("layers", {}).get("attn", {}) if isinstance(params, dict) else {}
    if "wqkv" in attn and tp > 1:
        raise ValueError(
            "params are in the fused wqkv layout, which cannot be tp-sharded "
            "— pass the canonical (unfused) tree when tp > 1"
        )
    # int8 trees from the streaming loader carry kernel_q, not kernel — they
    # skip fusion (concat of already-quantized kernels is possible but the
    # loader path targets 8B, where tp>1 or memory-tightness rules fusion out)
    if (
        not engine_config.fuse_matmuls
        or tp > 1
        or "wq" not in attn
        or "kernel" not in attn["wq"]
    ):
        return params, "wqkv" in attn
    return fuse_llama_params(params), True


def maybe_quantize_params(params, engine_config: EngineConfig):
    """Apply weight-only int8 quantization at engine construction when
    ``EngineConfig.weight_quant == "int8"``. Already-quantized trees (any
    ``kernel_q`` leaf — e.g. streamed in int8 by the loader) pass through.
    Returns ``(params, quantized?)``. The caller-passed bf16 tree is NOT
    donated — callers legitimately share one tree across engines — so both
    trees coexist transiently; at 8B scale quantize during the streaming
    load instead (``load_safetensors_params(..., quant="int8")``) and this
    becomes the pass-through case."""
    from rag_llm_k8s_tpu.models.llama import quantize_llama_params

    attn = params.get("layers", {}).get("attn", {}) if isinstance(params, dict) else {}
    already = any("kernel_q" in sub for sub in attn.values() if isinstance(sub, dict))
    if engine_config.weight_quant not in ("bf16", "int8"):
        raise ValueError(
            f"weight_quant={engine_config.weight_quant!r}: expected 'bf16' or 'int8'"
        )
    if engine_config.weight_quant != "int8" or already:
        return params, already
    return quantize_llama_params(params), True


@dataclass
class EngineStats:
    prefill_tokens: int = 0
    decode_tokens: int = 0
    generate_calls: int = 0
    # speculative decoding: verify forwards run (each emits >= 1 token) and
    # tokens emitted by them; emitted / verify_steps = measured acceptance
    # (tokens per verify forward, >= 1.0 — the counter VERDICT r4 asked the
    # e2e bench to report)
    spec_verify_steps: int = 0
    spec_emitted_tokens: int = 0
    # paged continuous draft-and-verify (engine/speculative.py): draft
    # tokens OFFERED to verify steps and the subset ACCEPTED (emitted as
    # drafted); rejected = drafted - accepted. The one-shot path cannot
    # split these (its matcher runs on device, acceptance is folded into
    # emitted/verify_steps), so they move only under spec_paged.
    spec_drafted_tokens: int = 0
    spec_accepted_tokens: int = 0
    # (row, verify-window) pairs that OFFERED drafts — the denominator of
    # the honest mean-accepted-length read: accepted_tokens/drafted_rows
    # (emitted/verify_steps is batch-summed and counts corrections, so it
    # floors at the active-row count even when acceptance is zero)
    spec_drafted_rows: int = 0
    # KV prefix cache: prompt tokens whose prefill was SKIPPED because their
    # KV was spliced from a cached block (prefill_tokens counts only tokens
    # actually computed — the two sum to the logical prompt-token total)
    prefill_tokens_skipped: int = 0


class InferenceEngine:
    """Owns params + compiled executables; thread-safe ``generate``."""

    def __init__(
        self,
        config: LlamaConfig,
        params,
        sampling: SamplingConfig = SamplingConfig(),
        engine_config: EngineConfig = EngineConfig(),
        dtypes: DTypePolicy = DTypePolicy(),
        mesh: Optional[MeshContext] = None,
        pad_id: int = 0,
    ):
        self.config = config
        self.sampling = sampling
        self.engine_config = engine_config
        self.dtypes = dtypes
        self.mesh = mesh
        self.pad_id = pad_id
        if engine_config.kv_quant not in ("bf16", "int8"):
            raise ValueError(
                f"kv_quant={engine_config.kv_quant!r}: expected 'bf16' or 'int8'"
            )
        if engine_config.speculative not in ("off", "prompt_lookup", "auto"):
            raise ValueError(
                f"speculative={engine_config.speculative!r}: expected "
                "'off', 'prompt_lookup' or 'auto'"
            )
        # adaptive speculation ("auto"): EMA of measured tokens-per-verify;
        # when the workload/model gives ~1.0 (lookup never hits), stop paying
        # the verify overhead, re-probing every _SPEC_REPROBE-th call
        self._spec_ema: Optional[float] = None
        self._spec_skips = 0
        self.params, fused = maybe_fuse_params(params, engine_config, mesh)
        self.params, quantized = maybe_quantize_params(self.params, engine_config)
        self.model = LlamaModel(
            config,
            dtypes,
            attn_impl=engine_config.attn_impl,
            mesh=(mesh.mesh if mesh is not None and mesh.tp > 1 else None),
            fused_qkv=fused,
            quantized=quantized,
            kv_quant=engine_config.kv_quant,
        )
        # same params, STATIC chunked=True: prompts longer than the largest
        # bucket prefill through the cache chunk by chunk (offset-causal
        # chunk_prefill_attention) instead of being silently truncated
        self.model_chunked = self.model.copy(chunked=True)
        self._compiled: Dict[Tuple[int, int, int, Optional[int]], jax.stages.Compiled] = {}
        # mesh-replicated chunk-token sidecar copies (see _placed_sidecar)
        self._sidecar_placed: Dict[Tuple[int, int], tuple] = {}
        self._lock = threading.Lock()
        self._rng_counter = 0
        self.stats = EngineStats()
        # goodput ledger (obs/goodput.py; ISSUE 14): the one-shot engine's
        # generate is ONE device program, so the roofline model splits each
        # call's measured duration into prefill/decode shares analytically
        # ("oneshot" windows; the continuous engine measures its windows
        # exactly). Journals a goodput_window flight event per call.
        self.ledger = obs_goodput.ledger_for(config, engine_config)
        # observability handles (obs/metrics.py): standalone engines report
        # into the process default registry; RagService rebinds to its own
        self.bind_metrics(obs_metrics.default_registry())
        # cross-request KV prefix cache (engine/prefix_cache.py): owns the
        # HBM-budgeted LRU of segment blocks; this engine provides the
        # build/splice/generate executables it drives
        self.prefix_cache = None
        self._prefix_zero = None  # lazily built all-zeros splice buffer
        if getattr(engine_config, "prefix_cache", None) is not None and \
                engine_config.prefix_cache.enabled:
            from rag_llm_k8s_tpu.engine.prefix_cache import PrefixCache

            self.prefix_cache = PrefixCache(engine_config.prefix_cache, self)

    # ------------------------------------------------------------------
    # observability (obs/metrics.py)
    # ------------------------------------------------------------------
    def bind_metrics(self, registry) -> None:
        """Point this engine's metric handles at ``registry`` — called at
        construction with the process default and again by RagService with
        the service's own registry, so one scrape carries the engine's
        compile events and generate/inter-token histograms."""
        self._obs = registry
        self._m_compile_events = registry.counter(
            "rag_compile_events_total", "AOT lowering/compile events"
        )
        self._m_compile_seconds = registry.counter(
            "rag_compile_seconds_total", "seconds spent in AOT lowering/compile"
        )
        self._m_generate = registry.histogram(
            "rag_generate_duration_seconds",
            "one generate call: prefill + decode + output fetch",
            buckets=obs_metrics.REQUEST_BUCKETS,
        )
        # the one-shot engine's whole generate is ONE device program, so
        # its per-token figure is an ESTIMATE (call duration / decode
        # steps, prefill share included) — labeled to distinguish it from
        # the continuous engine's exact per-window measurement
        self._m_itl = registry.labeled_histogram(
            "rag_decode_inter_token_seconds",
            "per-decoded-token latency (mode label: oneshot_est is call "
            "duration over decode steps; continuous is exact per window)",
            buckets=obs_metrics.TOKEN_LATENCY_BUCKETS,
        ).labels(mode="oneshot_est")

    def _record_compile(self, seconds: float) -> None:
        """Attribute one AOT lowering/compile to the dashboard ('first
        request is slow' becomes a visible compile event, not a mystery)."""
        self._m_compile_events.inc()
        self._m_compile_seconds.inc(seconds)

    def _observe_generate(self, seconds: float, decode_steps: int) -> None:
        self._m_generate.observe(seconds)
        self._m_itl.observe(seconds / max(decode_steps, 1))

    def _record_oneshot(
        self, call_s: float, bucket: int, batch: int, computed: int,
        decode_tokens: int, decode_steps: int, skipped: int = 0,
        info: Optional[Dict] = None,
    ) -> None:
        """Fold one generate call into the goodput ledger, journal its
        ``goodput_window`` event, and (when the caller passed an ``info``
        out-param) surface the per-request share for the /generate
        timings block."""
        w = self.ledger.record_oneshot(
            call_s, bucket=bucket, batch=batch, computed_tokens=computed,
            decode_tokens=decode_tokens, decode_steps=decode_steps,
            skipped=skipped,
        )
        if w is None:
            return
        per_row = w.pop("chip_ms_per_row")
        frac = w.pop("goodput_frac")
        flight.emit("goodput_window", **w)
        if info is not None:
            gp = {"chip_ms": per_row, "goodput_frac": frac}
            if self.ledger.chip_hour_usd > 0:
                gp["cost_usd"] = (
                    per_row / 1e3 / 3600.0 * self.ledger.chip_hour_usd
                )
            prev = info.get("goodput")
            if prev and prev.get("chip_ms"):
                # a chunked generate() calls this once per sub-batch with
                # ONE info dict: accumulate — overwriting would report
                # only the last chunk's share and under-bill the caller
                chip = prev["chip_ms"] + gp["chip_ms"]
                gp["goodput_frac"] = round(
                    (prev["chip_ms"] * prev.get("goodput_frac", 0.0)
                     + gp["chip_ms"] * frac) / chip, 6,
                )
                gp["chip_ms"] = round(chip, 4)
                if "cost_usd" in gp or "cost_usd" in prev:
                    gp["cost_usd"] = (
                        prev.get("cost_usd", 0.0) + gp.get("cost_usd", 0.0)
                    )
            info["goodput"] = gp

    # ------------------------------------------------------------------
    # compiled generate graph (one per (B, S, max_new))
    # ------------------------------------------------------------------
    def _build_generate(self, B: int, S: int, max_new: int, chunk: Optional[int] = None):
        """AOT-compile one generate executable.

        ``chunk=None``: single-shot prefill at bucket ``S``. ``chunk=C``:
        ``S`` is a multiple of ``C`` and the prompt prefills through the
        cache in ``C``-sized chunks (long prompts — no silent truncation).
        """
        gen = self._make_gen(B, S, max_new, chunk)
        # AOT-compile from abstract shapes (no execution)
        avals = param_avals(self.params)
        data_sharding = self.mesh.replicated if self.mesh is not None else None
        tok_aval = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=data_sharding)
        rng_aval = jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=data_sharding)
        return (
            jax.jit(gen)
            .lower(avals, tok_aval, tok_aval, rng_aval)
            .compile()
        )

    def _make_gen(self, B: int, S: int, max_new: int, chunk: Optional[int] = None):
        """The generate graph body ``gen(params, tokens, pad_mask, rng)`` —
        shared by the direct executable (`_build_generate`) and the
        device-assembled RAG variant (`_build_generate_rag`), which prepends
        on-device prompt assembly to the same body."""
        cfg, dt, sampling = self.config, self.dtypes, self.sampling
        model = self.model
        # cache length rounds up to a 128 multiple so the fused decode kernel
        # tiles it exactly AND the bf16 [.., T, hd] blocks meet Mosaic's
        # second-to-minor tile height even for tiny buckets; slots past
        # S + max_new never enter any kv window
        T = -(-(S + max_new) // 128) * 128
        eos_ids = cfg.eos_token_ids
        cache_dtype = dt.compute_dtype
        pad_id = self.pad_id

        def prefill(params, tokens, positions, cache, kv_start):
            if chunk is None:
                return model.apply(
                    {"params": params}, tokens, positions, cache,
                    kv_start, jnp.full((B,), S, jnp.int32), jnp.int32(0),
                    last_logit_only=True,
                )
            n_chunks = S // chunk
            mc = self.model_chunked

            def body(cache, ci):
                wi = ci * chunk
                tok_c = jax.lax.dynamic_slice(tokens, (0, wi), (B, chunk))
                pos_c = jax.lax.dynamic_slice(positions, (0, wi), (B, chunk))
                # last_logit_only also for interior chunks: their logits are
                # discarded, so never materialize a [B, C, V] projection
                _, cache = mc.apply(
                    {"params": params}, tok_c, pos_c, cache,
                    kv_start, jnp.broadcast_to(wi + chunk, (B,)).astype(jnp.int32),
                    wi.astype(jnp.int32), last_logit_only=True,
                )
                return cache, None

            if n_chunks > 1:
                cache, _ = jax.lax.scan(
                    body, cache, jnp.arange(n_chunks - 1, dtype=jnp.int32)
                )
            wi = (n_chunks - 1) * chunk
            return mc.apply(
                {"params": params}, tokens[:, wi:], positions[:, wi:], cache,
                kv_start, jnp.full((B,), S, jnp.int32), jnp.int32(wi),
                last_logit_only=True,
            )

        def gen(params, tokens, pad_mask, rng):
            cache = make_kv_cache(
                cfg, B, T, cache_dtype, quant=self.engine_config.kv_quant
            )
            kv_start, _ = mask_window(pad_mask)  # left-pad: [S - real_len, S)
            real_len = jnp.sum(pad_mask, axis=-1)  # [B]
            positions = jnp.clip(jnp.cumsum(pad_mask, axis=-1) - 1, 0)
            logits, cache = prefill(params, tokens, positions, cache, kv_start)
            rng, k0 = jax.random.split(rng)
            tok0 = sample_token(k0, logits[:, -1], sampling)
            done0 = _isin(tok0, eos_ids)
            out0 = jnp.full((B, max_new), pad_id, jnp.int32).at[:, 0].set(tok0)

            def cond(c):
                step, _, _, done, _, _ = c
                return (step < max_new) & ~jnp.all(done)

            def body(c):
                step, cache, last_tok, done, out, rng = c
                # feed token sampled at step-1: cache slot S+step-1, position real_len+step-1
                write_index = (S + step - 1).astype(jnp.int32)
                pos = (real_len + step - 1)[:, None].astype(jnp.int32)
                # the fed token's slot is written this call, so the valid
                # window runs through it: [kv_start, write_index + 1)
                kv_len = jnp.broadcast_to((write_index + 1).astype(jnp.int32), (B,))
                logits, cache = model.apply(
                    {"params": params},
                    last_tok[:, None],
                    pos,
                    cache,
                    kv_start,
                    kv_len,
                    write_index,
                )
                rng, k = jax.random.split(rng)
                tok = sample_token(k, logits[:, 0], sampling)
                tok = jnp.where(done, jnp.int32(eos_ids[0]), tok)
                done = done | _isin(tok, eos_ids)
                out = out.at[:, step].set(tok)
                return (step + 1, cache, tok, done, out, rng)

            init = (jnp.int32(1), cache, tok0, done0, out0, rng)
            _, _, _, _, out, _ = jax.lax.while_loop(cond, body, init)
            return out

        return gen

    def _build_generate_spec(self, S: int, max_new: int):
        """AOT-compile the SPECULATIVE batch-1 generate executable
        (``EngineConfig.speculative`` = "prompt_lookup"/"auto").

        Each loop iteration feeds ``k+1`` tokens — the pending last token
        plus the ``k`` tokens that followed the most recent in-context
        repeat of the trailing ``n``-gram — through the offset-causal
        chunked model (ONE forward ≈ one decode step's weight traffic),
        then keeps the longest accepted proposal prefix plus one correction
        token. Rejected proposals cost nothing to undo: the KV frontier
        simply doesn't advance over their slots, and later iterations
        overwrite them (the same windowed-mask machinery chunked prefill
        already relies on).

        Acceptance rule per position ``j`` with proposal ``x``:
        - **greedy** (``do_sample=False``): accept iff ``x`` equals the
          model's own argmax — output token-identical to the vanilla loop.
        - **sampled** (``do_sample=True``): REJECTION SAMPLING against the
          deterministic draft: accept with probability ``p_j(x)`` under the
          temperature/top-p-filtered target distribution; on rejection emit
          a draw from the residual (``p_j`` with ``x`` masked, renormalized
          — for a point-mass draft the residual of ``max(p-q, 0)`` is
          exactly that); on full acceptance emit a bonus draw from ``p_k``.
          Marginally each emitted token is distributed exactly as one
          vanilla sampling step given its prefix: ``P(x) = p(x)`` (accept)
          and ``P(y≠x) = (1-p(x))·p(y)/(1-p(x)) = p(y)`` (reject) — the
          emitted DISTRIBUTION equals vanilla 0.7/0.9 sampling
          (tests/test_speculative.py::TestSampledDistribution), though the
          stream for a pinned seed differs (different rng consumption).
        """
        gen = self._make_gen_spec(S, max_new)
        avals = param_avals(self.params)
        data_sharding = self.mesh.replicated if self.mesh is not None else None
        tok_aval = jax.ShapeDtypeStruct((1, S), jnp.int32, sharding=data_sharding)
        rng_aval = jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=data_sharding)
        return jax.jit(gen).lower(avals, tok_aval, tok_aval, rng_aval).compile()

    def _make_gen_spec(self, S: int, max_new: int):
        """The speculative batch-1 graph body (see ``_build_generate_spec``)
        — shared with the device-assembled RAG variant."""
        cfg, dt = self.config, self.dtypes
        model = self.model
        mc = self.model_chunked
        sampling = self.sampling
        sampled = sampling.do_sample and sampling.temperature > 0.0
        n = max(1, self.engine_config.spec_ngram)
        k = max(1, self.engine_config.spec_tokens)
        # k extra cache slots: the LAST verify forward can start as late as
        # slot S+max_new-2 and still writes k+1 slots. Without the slack,
        # dynamic_update_slice CLAMPS the out-of-range write start, silently
        # shifting the whole block left over valid accepted-token KV — the
        # exactness contract would break precisely near the token budget.
        T = -(-(S + max_new + k) // 128) * 128
        eos_ids = cfg.eos_token_ids
        cache_dtype = dt.compute_dtype
        pad_id = self.pad_id
        i32 = jnp.int32

        def gen(params, tokens, pad_mask, rng):
            cache = make_kv_cache(
                cfg, 1, T, cache_dtype, quant=self.engine_config.kv_quant
            )
            kv_start, _ = mask_window(pad_mask)
            real_len = jnp.sum(pad_mask, axis=-1)  # [1]
            positions = jnp.clip(jnp.cumsum(pad_mask, axis=-1) - 1, 0)
            logits, cache = model.apply(
                {"params": params}, tokens, positions, cache,
                kv_start, jnp.full((1,), S, i32), i32(0),
                last_logit_only=True,
            )
            rng, k0 = jax.random.split(rng)
            tok0 = sample_token(k0, logits[:, -1], sampling)  # [1]
            done0 = _isin(tok0, eos_ids)[0]
            # out and hist carry k+1 slack slots: every scatter below then
            # uses UNIQUE per-lane indices (e + j / wi + 1 + j) — clipping
            # into the last slot instead would create duplicate indices,
            # and XLA scatter picks an arbitrary winner among duplicates
            out0 = jnp.full((1, max_new + k + 1), pad_id, i32).at[:, 0].set(tok0)
            # token history mirrors cache slots: prompt at [0, S) (left-
            # padded exactly like the cache), emitted token j at S + j
            hist0 = jnp.full((1, T + k + 1), pad_id, i32)
            hist0 = jax.lax.dynamic_update_slice(hist0, tokens, (0, 0))
            hist0 = hist0.at[:, S].set(tok0)

            def cond(c):
                e, _, _, done, _, _, _ = c
                return (e < max_new) & ~done

            def body(c):
                e, cache, hist, done, out, rng, iters = c
                wi = (S + e - 1).astype(i32)  # slot of the pending token
                row = hist[0]
                last_tok = jax.lax.dynamic_slice(row, (wi,), (1,))  # [1]
                # ---- propose: last occurrence of the trailing n-gram ----
                match = jnp.ones((T + k + 1,), bool)
                for j in range(n):
                    tj = jax.lax.dynamic_slice(row, (wi - j,), (1,))[0]
                    # candidate c matches iff hist[c - j] == hist[wi - j];
                    # roll wraps but candidates below kv_start+n-1 are
                    # masked out, so wrapped lanes never survive
                    match = match & (jnp.roll(row, j) == tj)
                idx = jnp.arange(T + k + 1, dtype=i32)
                # only occurrences whose k-token continuation is already
                # WRITTEN (idx + k <= wi) may propose: the frontier's own
                # trailing gram always matches itself but continues into
                # unwritten pad history — measured on the chain-head 8B
                # it capped acceptance at ~2 tokens/verify (accept one,
                # reject at the first pad, every verify)
                match = match & (idx >= kv_start[0] + n - 1) & (idx + k <= wi)
                c_star = jnp.max(jnp.where(match, idx, -1))
                src = jnp.where(c_star >= 0, c_star + 1, 0).astype(i32)
                props = jax.lax.dynamic_slice(row, (src,), (k,))  # [k]
                # (no-match proposals are arbitrary history — harmless:
                # acceptance only ever keeps tokens equal to the greedy
                # choice, so garbage proposals just mean m = 0)
                fed = jnp.concatenate([last_tok, props])[None, :]  # [1, k+1]
                pos = (real_len[0] - 1 + e + jnp.arange(k + 1, dtype=i32))[None, :]
                kv_len = jnp.full((1,), wi + k + 1, i32)
                logits, cache = mc.apply(
                    {"params": params}, fed, pos, cache, kv_start, kv_len, wi
                )
                j_idx = jnp.arange(k + 1, dtype=i32)
                if not sampled:
                    # greedy: accept iff the proposal IS the argmax; position
                    # m then carries the correction argmax — token-identical
                    # to the vanilla greedy loop by construction
                    g = jnp.argmax(logits[0], axis=-1).astype(i32)  # [k+1]
                    acc = jnp.cumprod((props == g[:k]).astype(i32))
                    m = jnp.sum(acc)
                else:
                    # rejection sampling vs the point-mass draft (docstring):
                    # accept proposal x_j w.p. p_j(x_j); on rejection draw
                    # from p_j with x_j masked (the normalized residual of
                    # max(p - q, 0) for q = δ_x); on full acceptance draw the
                    # bonus token from p_k. Emitted marginal == vanilla
                    # sampling exactly, per position given its prefix.
                    prepared = _prepared_logits(logits[0], sampling)  # [k+1, V]
                    probs = jax.nn.softmax(prepared, axis=-1)
                    rng, it_key = jax.random.split(rng)
                    ku, kr = jax.random.split(it_key)
                    p_prop = jnp.take_along_axis(
                        probs[:k], props[:, None], axis=-1
                    )[:, 0]  # [k]
                    accept = jax.random.uniform(ku, (k,)) < p_prop
                    acc = jnp.cumprod(accept.astype(i32))
                    m = jnp.sum(acc)
                    res = prepared[:k].at[jnp.arange(k), props].set(NEG_INF)
                    rkeys = jax.random.split(kr, k + 1)
                    r = jax.vmap(jax.random.categorical)(rkeys[:k], res)
                    bonus = jax.random.categorical(rkeys[k], prepared[k])
                    corr = jnp.where(
                        m < k, r[jnp.minimum(m, k - 1)], bonus
                    ).astype(i32)
                    # accepted positions emit their proposal; position m the
                    # correction/bonus draw (slots past m are never emitted)
                    g = jnp.concatenate([props, bonus[None].astype(i32)])
                    g = jnp.where(j_idx == m, corr, g)
                is_eos = _isin(g, eos_ids)
                eos_pos = jnp.min(jnp.where(is_eos & (j_idx <= m), j_idx, k + 1))
                m_eff = jnp.minimum(jnp.minimum(m, eos_pos), max_new - e - 1)
                emit = j_idx <= m_eff
                out_idx = e + j_idx  # unique lanes (slack-padded buffer)
                out_row = out[0].at[out_idx].set(
                    jnp.where(emit, g, out[0][out_idx])
                )
                hist_idx = wi + 1 + j_idx
                hist_row = row.at[hist_idx].set(jnp.where(emit, g, row[hist_idx]))
                done = done | (eos_pos <= m_eff)
                return (
                    e + m_eff + 1, cache, hist_row[None], done, out_row[None],
                    rng, iters + 1,
                )

            init = (i32(1), cache, hist0, done0, out0, rng, i32(0))
            _, _, _, _, out, _, iters = jax.lax.while_loop(cond, body, init)
            # iters = verify forwards run; the emitted-token count over it
            # is the measured acceptance rate (EngineStats.spec_verify_steps).
            # Packed into the out buffer's first slack slot (never an
            # emission target): returning it as a second array would cost a
            # SECOND device->host round trip per generate on a slow link.
            return out[:, :max_new + 1].at[:, max_new].set(iters)

        return gen

    def _build_generate_rag(
        self, S: int, max_new: int, cap: int, Lc: int, LA: int, LB: int,
        n: int, kk: int, spec: bool,
    ):
        """AOT-compile the SINGLE-FETCH RAG executable: device-side prompt
        assembly fused in front of the (vanilla or speculative) batch-1
        generate body.

        The retrieved top-k never leaves HBM before generation: inputs are
        the fused retrieve's packed ``[1, 2k]`` output (dists ‖ ids, fp32),
        the store's chunk-token sidecar ``[cap, Lc]``/``[cap]``, the fixed
        prompt head ``a_ids`` (BOS + system message + "\\n\\nContext: ") and
        per-query tail ``b_ids`` ("\\n\\nUser: {q}\\n\\nChatbot:", padded to
        ``LB``). Assembly gathers the top-``n`` chunk rows, keeps the
        longest prefix of chunks that fits ``S - LA - b_len`` (token-level
        truncation of the first chunk if even it alone overflows — the
        device mirror of the host path's budget shrinking), writes the
        segments left-packed against the right edge, and hands the
        assembled ``(tokens, pad_mask)`` to the shared generate body. The
        host sees ONE fetch per query: the output tokens (the retrieve ids
        fetch for the response's context text overlaps generation).
        """
        inner = (
            self._make_gen_spec(S, max_new) if spec
            else self._make_gen(1, S, max_new, None)
        )
        pad_id = self.pad_id
        i32 = jnp.int32

        def gen_rag(params, a_ids, b_ids, b_len, packed, store_toks, store_lens, rng):
            idx = packed[0, kk : kk + n].astype(i32)  # top-n rows, rank order
            safe = jnp.clip(idx, 0, cap - 1)
            rows = store_toks[safe]  # [n, Lc] gather
            lens = store_lens[safe]  # [n]
            avail = jnp.maximum(S - LA - b_len, 0)
            keep = jnp.cumsum(lens) <= avail  # monotone: a kept prefix
            eff = jnp.where(keep, lens, 0)
            # never drop ALL context: chunk 0 truncates to the budget instead
            eff = eff.at[0].set(
                jnp.where(keep[0], lens[0], jnp.minimum(lens[0], avail))
            )
            total = (LA + jnp.sum(eff) + b_len).astype(i32)
            start = S - total
            # one slack slot at S + Lc - 1 absorbs every masked-out lane:
            # real writes always land < S (proved by total <= S), so the
            # junk slot never collides with a real token
            buf = jnp.full((S + Lc,), pad_id, i32)
            buf = jax.lax.dynamic_update_slice(buf, a_ids, (start,))
            off = start + LA + jnp.concatenate(
                [jnp.zeros((1,), i32), jnp.cumsum(eff)[:-1].astype(i32)]
            )
            lane = jnp.arange(Lc, dtype=i32)
            for i in range(n):  # static unroll over the top-n chunks
                valid = lane < eff[i]
                tgt = jnp.where(valid, off[i] + lane, S + Lc - 1)
                buf = buf.at[tgt].set(jnp.where(valid, rows[i], buf[tgt]))
            laneb = jnp.arange(LB, dtype=i32)
            validb = laneb < b_len
            tgtb = jnp.where(validb, S - b_len + laneb, S + Lc - 1)
            buf = buf.at[tgtb].set(jnp.where(validb, b_ids, buf[tgtb]))
            tokens = buf[:S][None, :]
            pad_mask = (jnp.arange(S) >= start).astype(i32)[None, :]
            return inner(params, tokens, pad_mask, rng)

        avals = param_avals(self.params)
        ds = self.mesh.replicated if self.mesh is not None else None
        mk = lambda shape, dtype: jax.ShapeDtypeStruct(shape, dtype, sharding=ds)  # noqa: E731
        return (
            jax.jit(gen_rag)
            .lower(
                avals,
                mk((LA,), jnp.int32),
                mk((LB,), jnp.int32),
                mk((), jnp.int32),
                mk((1, 2 * kk), jnp.float32),
                mk((cap, Lc), jnp.int32),
                mk((cap,), jnp.int32),
                mk((2,), jnp.uint32),
            )
            .compile()
        )

    def generate_rag(
        self,
        a_ids: np.ndarray,
        b_ids: np.ndarray,
        packed,
        store_toks,
        store_lens,
        n_chunks: int,
        max_new_tokens: Optional[int] = None,
        seed: Optional[int] = None,
        info: Optional[Dict] = None,  # out-param: per-request goodput share
    ) -> List[int]:
        """Single-fetch RAG generate (see ``_build_generate_rag``): the
        caller hands DEVICE arrays for the packed retrieve output and the
        chunk-token sidecar; only the final output tokens cross to the host.
        Always serves at the LARGEST prompt bucket (full-context RAG prompts
        land there; the caller guards that head + tail fit it)."""
        S = max(self.engine_config.prompt_buckets)
        max_new = (
            self.sampling.max_new_tokens if max_new_tokens is None else max_new_tokens
        )
        max_new = self._clamp_max_new(S, max_new)
        a = np.asarray(a_ids, np.int32)
        b = np.asarray(b_ids, np.int32)
        LA = int(a.shape[0])
        # FIXED tail bucket: one executable per store shape instead of a
        # per-question-length ladder (warmup can then cover every solo
        # query exactly; 128 scatter lanes are free next to the model).
        # Tails beyond it are the caller's fallback (host path).
        LB = self.RAG_TAIL_BUCKET
        if b.shape[0] > LB:
            raise ValueError(
                f"prompt tail of {b.shape[0]} tokens exceeds the fused "
                f"bucket ({LB}) — route this query through the host path"
            )
        b_pad = np.full((LB,), self.pad_id, np.int32)
        b_pad[: b.shape[0]] = b
        cap, Lc = int(store_toks.shape[0]), int(store_toks.shape[1])
        kk = int(packed.shape[1]) // 2
        n = min(n_chunks, kk)
        spec = self._spec_applicable(1, None)
        fn = self._get_rag_compiled(S, max_new, cap, Lc, LA, LB, n, kk, spec)
        rng = self._next_rng(seed)
        a_j, b_j = jnp.asarray(a), jnp.asarray(b_pad)
        blen_j, rng_j = jnp.int32(b.shape[0]), rng
        if self.mesh is not None:
            # the executable was lowered with replicated data shardings:
            # place the small per-query inputs each call, and the store
            # sidecar ONCE per snapshot (broadcasting [cap, Lc] per query
            # would be a full-sidecar transfer at corpus scale — the pair
            # is immutable, so cache the placed copy keyed by identity)
            rep = self.mesh.replicated
            a_j, b_j, blen_j, packed, rng_j = (
                jax.device_put(x, rep) for x in (a_j, b_j, blen_j, packed, rng)
            )
            store_toks, store_lens = self._placed_sidecar(store_toks, store_lens)
        t_call = time.perf_counter()
        out = np.asarray(
            fn(
                self.params, a_j, b_j, blen_j, packed, store_toks, store_lens,
                rng_j,
            )
        )  # the ONE per-query fetch
        call_s = time.perf_counter() - t_call
        iters = 0
        if spec:
            iters = int(out[0, max_new])
            out = out[:, :max_new]
        eos = set(self.config.eos_token_ids)
        row: List[int] = []
        for t in out[0]:
            if int(t) in eos:
                break
            row.append(int(t))
        spec_accept = None
        if spec and iters > 0:
            emitted = len(row) + (1 if len(row) < max_new else 0) - 1
            self._spec_record(max(emitted, 0), iters)
            spec_accept = round(max(emitted, 0) / iters, 4)
        self._observe_generate(call_s, len(row))
        with self._lock:
            self.stats.generate_calls += 1
            self.stats.decode_tokens += len(row)
            # prompt length is decided on device; the head + tail are the
            # host-known share (the service adds the gathered chunk share
            # post-hoc once the ids fetch lands — record_prefill)
            self.stats.prefill_tokens += LA + int(b.shape[0])
        # goodput ledger: the assembled prompt length is decided ON DEVICE
        # (fetching it would put a round-trip back on the path this mode
        # exists to remove), so the computed-token figure is the host-known
        # head + tail plus an n-chunks × max-segment ESTIMATE of the
        # gathered share, clamped to the bucket — category split and MFU
        # for this kind are estimates by construction (docs/GOODPUT.md)
        self._record_oneshot(
            call_s, bucket=S, batch=1,
            computed=min(LA + int(b.shape[0]) + n * Lc, S),
            decode_tokens=len(row), decode_steps=max(len(row), 1),
            info=info,
        )
        if info is not None and spec_accept is not None and self.ledger.enabled:
            info.setdefault("goodput", {})["spec_accept_len_mean"] = spec_accept
        if info is not None and spec and iters > 0:
            # approximation fingerprint (obs/shadow.py): see generate()
            ap = info.setdefault("approx", [])
            if "spec_verify" not in ap:
                ap.append("spec_verify")
        return row

    def _get_rag_compiled(
        self, S: int, max_new: int, cap: int, Lc: int, LA: int, LB: int,
        n: int, kk: int, spec: bool,
    ):
        """Get-or-build the single-fetch RAG executable; under
        ``speculative="auto"`` BOTH the spec and vanilla variants build (the
        EMA can flip between them mid-serving — a flip must never compile
        inside a timed request)."""
        variants = [spec]
        if self.engine_config.speculative == "auto":
            variants = [spec, not spec]
        fn = None
        for v in variants:
            key = (1, S, max_new, ("rag", cap, Lc, LA, LB, n, kk, v))
            with self._lock:
                built = self._compiled.get(key)
            if built is None:
                t0 = time.perf_counter()
                built = self._build_generate_rag(S, max_new, cap, Lc, LA, LB, n, kk, v)
                self._record_compile(time.perf_counter() - t0)
                with self._lock:
                    self._compiled.setdefault(key, built)
                    built = self._compiled[key]
            if v == spec:
                fn = built
        return fn

    def warm_rag(
        self, a_len: int, cap: int, Lc: int, kk: int, n: int,
        max_new_tokens: Optional[int] = None,
    ) -> None:
        """AOT-compile the single-fetch RAG executables for the given store
        shapes (compile only, nothing executes) — called by the service's
        warmup and its post-ingest hook so production queries never pay the
        compile. The tail bucket is FIXED (``RAG_TAIL_BUCKET``), so this
        covers every solo query the fused path will serve."""
        S = max(self.engine_config.prompt_buckets)
        max_new = (
            self.sampling.max_new_tokens if max_new_tokens is None else max_new_tokens
        )
        max_new = self._clamp_max_new(S, max_new)
        spec = self.engine_config.speculative in ("prompt_lookup", "auto")
        self._get_rag_compiled(
            S, max_new, cap, Lc, a_len, self.RAG_TAIL_BUCKET, n, kk, spec
        )

    def _placed_sidecar(self, store_toks, store_lens):
        """Mesh-replicated copy of the (immutable) chunk-token sidecar,
        broadcast once per snapshot identity instead of per query. Holds a
        reference to the source pair so its id() cannot be recycled. ONE
        entry only: at the 64k-row cap a generation is ~0.5 GB (source +
        replicated), so keeping superseded generations would pin real HBM —
        a snapshot swap pays one re-broadcast and frees the old pair."""
        key = (id(store_toks), id(store_lens))
        with self._lock:
            cached = self._sidecar_placed.get(key)
        if cached is not None:
            return cached[1]
        rep = self.mesh.replicated
        placed = (
            jax.device_put(store_toks, rep), jax.device_put(store_lens, rep)
        )
        with self._lock:
            self._sidecar_placed.clear()
            self._sidecar_placed[key] = ((store_toks, store_lens), placed)
        return placed

    def drop_placed_sidecar(self) -> None:
        """Release the mesh-replicated sidecar copy (service shutdown —
        ``VectorStore.release_token_device`` cannot reach this cache)."""
        with self._lock:
            self._sidecar_placed.clear()

    def record_prefill(self, n_tokens: int) -> None:
        """Post-hoc prefill-token accounting for device-assembled prompts
        (the chunk share is only known once the ids fetch lands)."""
        with self._lock:
            self.stats.prefill_tokens += int(n_tokens)

    # ------------------------------------------------------------------
    # KV prefix cache (engine/prefix_cache.py drives these)
    # ------------------------------------------------------------------
    def _prefix_capacity(self) -> int:
        return self.engine_config.prefix_cache.max_prefix_tokens

    def _prefix_plane_shapes(self, length: int):
        """(shape, dtype) per cache plane for a ``length``-slot KV block —
        payloads first, then (int8-KV) the fp32 scale planes."""
        c = self.config
        cdt = (
            jnp.int8 if self.engine_config.kv_quant == "int8"
            else self.dtypes.compute_dtype
        )
        pay = ((c.num_layers, 1, c.num_kv_heads, length, c.head_dim), cdt)
        out = [pay, pay]
        if self.engine_config.kv_quant == "int8":
            sc = ((c.num_layers, 1, c.num_kv_heads, length), jnp.float32)
            out += [sc, sc]
        return out

    def _prefix_plane_avals(self, length: int):
        ds = self.mesh.replicated if self.mesh is not None else None
        return tuple(
            jax.ShapeDtypeStruct(s, d, sharding=ds)
            for s, d in self._prefix_plane_shapes(length)
        )

    def prefix_buffer_zero(self):
        """The shared all-zeros ``[L, 1, K, P, hd]`` splice buffer every
        prefix assembly starts from (immutable — splices produce new
        buffers, so one instance serves all threads). Built OUTSIDE the
        lock: the multi-MiB device transfer must not serialize concurrent
        resolves behind first-touch init (two racing builders waste one
        allocation of an immutable buffer; first install wins)."""
        with self._lock:
            cached = self._prefix_zero
        if cached is not None:
            return cached
        planes = tuple(
            jnp.zeros(s, d)
            for s, d in self._prefix_plane_shapes(self._prefix_capacity())
        )
        if self.mesh is not None:
            planes = tuple(
                jax.device_put(p, self.mesh.replicated) for p in planes
            )
        with self._lock:
            if self._prefix_zero is None:
                self._prefix_zero = planes
            return self._prefix_zero

    def splice_prefix(self, buf, block, offset: int):
        """Splice a segment block into a prefix buffer at slot ``offset``."""
        return _splice_prefix_planes(buf, block, jnp.int32(offset))

    def rerotate_segment_kv(self, planes, delta: int):
        """Position-shift a cached segment block by ``delta`` tokens: the
        chunk-granular reuse primitive (closed-form RoPE re-rotation of the
        K planes; V passes through). Handles both the native bf16 pair and
        the int8 4-tuple layout (dequant → rotate → requant)."""
        from rag_llm_k8s_tpu.models.llama import rerotate_prefix_planes

        return rerotate_prefix_planes(self.config, planes, delta)

    @staticmethod
    def slice_prefix_block(block, width: int):
        """The first ``width`` slots of a segment block (payloads
        ``[L, 1, K, Sb, hd]``, scales ``[L, 1, K, Sb]`` — the slot axis is
        3 in both): the boundary-correction pass builds a bucket-padded
        block but must overwrite ONLY its corrected window, or the splice
        would clobber the chunk's re-rotated tail with builder padding."""
        return tuple(
            p[:, :, :, :width] if p.ndim == 4 else p[:, :, :, :width, :]
            for p in block
        )

    def build_segment_kv(self, ids: Sequence[int], ctx_planes, ctx_len: int):
        """Prefill ONE prompt segment with ``ctx_planes[:ctx_len]`` as its
        left context and return its KV block padded to the segment bucket —
        the prefix cache's miss-path builder. Counts as real prefill work
        in the stats (the tokens ARE computed, once)."""
        pc = self.engine_config.prefix_cache
        Sb = bucket_len(max(len(ids), 1), pc.segment_buckets)
        toks = np.full((1, Sb), self.pad_id, np.int32)
        toks[0, : len(ids)] = ids
        fn = self._get_segment_kv(Sb)
        toks_j = jnp.asarray(toks)
        slen_j, clen_j = jnp.int32(len(ids)), jnp.int32(ctx_len)
        if self.mesh is not None:
            rep = self.mesh.replicated
            toks_j, slen_j, clen_j = (
                jax.device_put(x, rep) for x in (toks_j, slen_j, clen_j)
            )
            ctx_planes = tuple(jax.device_put(p, rep) for p in ctx_planes)
        block = fn(self.params, toks_j, slen_j, ctx_planes, clen_j)
        with self._lock:
            self.stats.prefill_tokens += len(ids)
        return block

    def _get_segment_kv(self, Sb: int):
        key = (1, Sb, 0, ("segkv", self._prefix_capacity()))
        with self._lock:
            fn = self._compiled.get(key)
        if fn is None:
            t0 = time.perf_counter()
            fn = self._build_segment_kv(Sb)
            self._record_compile(time.perf_counter() - t0)
            with self._lock:
                self._compiled.setdefault(key, fn)
                fn = self._compiled[key]
        return fn

    def _build_segment_kv(self, Sb: int):
        """AOT-compile the segment-KV builder: chunked prefill of up to
        ``Sb`` fresh tokens at a dynamic offset over a spliced context
        prefix, returning the fresh slots' KV block. One executable per
        segment bucket — never per (segment, offset) pair (both the offset
        and the real length are dynamic scalars)."""
        cfg, dt = self.config, self.dtypes
        mc = self.model_chunked
        P = self._prefix_capacity()
        T = -(-(P + Sb) // 128) * 128
        kvq = self.engine_config.kv_quant
        i32 = jnp.int32

        def seg(params, tokens, seg_len, ctx, ctx_len):
            cache = make_kv_cache(cfg, 1, T, dt.compute_dtype, quant=kvq)
            planes = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kvq == "int8" else (cache.k, cache.v)
            )
            # context splices at slot 0; its garbage tail (>= ctx_len) is
            # overwritten by this segment's own K/V write below
            planes = tuple(
                jax.lax.dynamic_update_slice(c, b.astype(c.dtype), (0,) * c.ndim)
                for c, b in zip(planes, ctx)
            )
            clen = ctx_len.astype(i32)
            positions = (clen + jnp.arange(Sb, dtype=i32))[None, :]
            kv_len = jnp.broadcast_to(clen + seg_len, (1,)).astype(i32)
            _, cache = mc.apply(
                {"params": params}, tokens, positions, KVCache(*planes),
                jnp.zeros((1,), i32), kv_len, clen, last_logit_only=True,
            )
            out = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kvq == "int8" else (cache.k, cache.v)
            )
            return tuple(
                jax.lax.dynamic_slice(
                    c,
                    (0, 0, 0, clen) + ((0,) if c.ndim == 5 else ()),
                    c.shape[:3] + (Sb,) + c.shape[4:],
                )
                for c in out
            )

        ds = self.mesh.replicated if self.mesh is not None else None
        out_shardings = (
            tuple(ds for _ in self._prefix_plane_shapes(Sb))
            if self.mesh is not None else None
        )
        return (
            jax.jit(seg, out_shardings=out_shardings)
            .lower(
                param_avals(self.params),
                jax.ShapeDtypeStruct((1, Sb), jnp.int32, sharding=ds),
                jax.ShapeDtypeStruct((), jnp.int32, sharding=ds),
                self._prefix_plane_avals(P),
                jax.ShapeDtypeStruct((), jnp.int32, sharding=ds),
            )
            .compile()
        )

    # ------------------------------------------------------------------
    # exact-path shadow scoring (obs/shadow.py drives this)
    # ------------------------------------------------------------------
    # chunk width for the teacher-forced scorer: bounds the materialized
    # [1, C, V] logit plane (the scorer needs EVERY position's logits,
    # unlike serving prefill) — 256 × a 128k vocab is ~130 MB fp32
    _SCORE_CHUNK = 256

    def score_exact(self, prompt_ids: Sequence[int],
                    emitted_ids: Sequence[int]) -> Dict[str, object]:
        """Teacher-forced EXACT-PATH scoring for the shadow quality
        auditor: ONE chunked forward over ``prompt + emitted`` with no
        prefix reuse, no speculation, and the engine's native KV dtype —
        the reference every serving-path approximation is judged against.

        Returns per-emitted-position arrays (length ``len(emitted_ids)``):
        ``argmax`` — the exact path's greedy choice given the DELIVERED
        prefix, ``max_logit`` / ``chosen_logit`` — the exact logit of that
        choice and of the delivered token (their gap is the divergence
        evidence obs/shadow.py folds into ``logit_err``). Raises
        ValueError on shapes past the chunked-prefill cap (the auditor
        skips those as "oversize").

        Argmax equivalence between this one forward and the step-by-step
        decode loop is the property the speculative verify paths already
        pin (their multi-token forwards must emit the vanilla loop's
        tokens byte-identically), so a greedy byte-identity contract
        audits clean here by construction.
        """
        x = [int(t) for t in prompt_ids] + [int(t) for t in emitted_ids]
        W = len(emitted_ids)
        if W == 0 or len(x) < 2:
            raise ValueError("score_exact needs a prompt and >= 1 emitted token")
        cap = self.engine_config.max_chunked_prompt
        if len(x) > cap:
            raise ValueError(
                f"score_exact sequence of {len(x)} tokens exceeds "
                f"max_chunked_prompt={cap}"
            )
        chunk = min(self._SCORE_CHUNK, max(self.engine_config.prompt_buckets))
        S = -(-len(x) // chunk) * chunk
        off = S - len(x)
        tokens = np.full((1, S), self.pad_id, np.int32)
        tokens[0, off:] = x
        mask = np.zeros((1, S), np.int32)
        mask[0, off:] = 1
        nxt = np.zeros((1, S), np.int32)
        nxt[0, : S - 1] = tokens[0, 1:]
        fn = self._get_score_exact(S, chunk)
        tokens_j, mask_j = jnp.asarray(tokens), jnp.asarray(mask)
        nxt_j = jnp.asarray(nxt)
        if self.mesh is not None:
            rep = self.mesh.replicated
            tokens_j, mask_j, nxt_j = (
                jax.device_put(v, rep) for v in (tokens_j, mask_j, nxt_j)
            )
        stats = np.asarray(fn(self.params, tokens_j, mask_j, nxt_j))
        lo = off + len(x) - W - 1  # slot whose logits predict emitted[0]
        sl = slice(lo, lo + W)
        return {
            "argmax": stats[sl, 0].astype(np.int64),
            "max_logit": stats[sl, 1].astype(np.float64),
            "chosen_logit": stats[sl, 2].astype(np.float64),
        }

    def _get_score_exact(self, S: int, chunk: int):
        key = (1, S, 0, ("shadow", chunk))
        with self._lock:
            fn = self._compiled.get(key)
        if fn is None:
            t0 = time.perf_counter()
            fn = self._build_score_exact(S, chunk)
            self._record_compile(time.perf_counter() - t0)
            with self._lock:
                self._compiled.setdefault(key, fn)
                fn = self._compiled[key]
        return fn

    def _build_score_exact(self, S: int, chunk: int):
        """AOT-compile the teacher-forced scorer: left-padded chunked
        prefill over the full sequence, reducing each chunk's [1, C, V]
        logit plane on device to per-position (argmax, max logit, logit of
        the next delivered token) — the host fetches one [S, 3] array,
        never a logit plane."""
        cfg, dt = self.config, self.dtypes
        mc = self.model_chunked
        T = -(-S // 128) * 128
        kvq = self.engine_config.kv_quant
        i32 = jnp.int32

        def score(params, tokens, pad_mask, next_tokens):
            cache = make_kv_cache(cfg, 1, T, dt.compute_dtype, quant=kvq)
            kv_start, _ = mask_window(pad_mask)
            positions = jnp.clip(jnp.cumsum(pad_mask, axis=-1) - 1, 0)
            n_chunks = S // chunk

            def body(carry, ci):
                cache, stats = carry
                wi = (ci * chunk).astype(i32)
                tok_c = jax.lax.dynamic_slice(tokens, (0, wi), (1, chunk))
                pos_c = jax.lax.dynamic_slice(positions, (0, wi), (1, chunk))
                nxt_c = jax.lax.dynamic_slice(next_tokens, (0, wi), (1, chunk))
                logits, cache = mc.apply(
                    {"params": params}, tok_c, pos_c, cache,
                    kv_start, jnp.broadcast_to(wi + chunk, (1,)).astype(i32),
                    wi,
                )
                row = logits[0].astype(jnp.float32)  # [chunk, V]
                amax = jnp.argmax(row, axis=-1)
                mx = jnp.max(row, axis=-1)
                chosen = jnp.take_along_axis(
                    row, nxt_c[0][:, None], axis=-1
                )[:, 0]
                stats = jax.lax.dynamic_update_slice(
                    stats,
                    jnp.stack(
                        [amax.astype(jnp.float32), mx, chosen], axis=-1
                    ),
                    (wi, jnp.int32(0)),
                )
                return (cache, stats), None

            init = (cache, jnp.zeros((S, 3), jnp.float32))
            (_, stats), _ = jax.lax.scan(
                body, init, jnp.arange(n_chunks, dtype=i32)
            )
            return stats

        ds = self.mesh.replicated if self.mesh is not None else None
        return (
            jax.jit(score, out_shardings=ds)
            .lower(
                param_avals(self.params),
                jax.ShapeDtypeStruct((1, S), jnp.int32, sharding=ds),
                jax.ShapeDtypeStruct((1, S), jnp.int32, sharding=ds),
                jax.ShapeDtypeStruct((1, S), jnp.int32, sharding=ds),
            )
            .compile()
        )

    def _make_gen_prefixed(self, S_suf: int, max_new: int):
        """The prefixed generate body: splice a CachedPrefix buffer into a
        fresh cache, chunk-prefill only the (right-padded) suffix at the
        dynamic prefix frontier, then run the standard decode loop. Prefix
        and suffix lengths are DYNAMIC scalars — every hit pattern reuses
        the one ``(P, S_suf, max_new)`` executable."""
        cfg, dt, sampling = self.config, self.dtypes, self.sampling
        model = self.model
        mc = self.model_chunked
        P = self._prefix_capacity()
        T = -(-(P + S_suf + max_new) // 128) * 128
        eos_ids = cfg.eos_token_ids
        kvq = self.engine_config.kv_quant
        pad_id = self.pad_id
        i32 = jnp.int32

        def gen(params, prefix_kv, prefix_len, tokens, suffix_len, rng):
            cache = make_kv_cache(cfg, 1, T, dt.compute_dtype, quant=kvq)
            planes = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kvq == "int8" else (cache.k, cache.v)
            )
            planes = tuple(
                jax.lax.dynamic_update_slice(c, b.astype(c.dtype), (0,) * c.ndim)
                for c, b in zip(planes, prefix_kv)
            )
            cache = KVCache(*planes)
            plen = prefix_len.astype(i32)
            slen = suffix_len.astype(i32)
            total = plen + slen
            kv_start = jnp.zeros((1,), i32)  # left-ALIGNED batch-1 layout
            # suffix is right-padded: pad K/V land in [total, plen + S_suf),
            # outside every kv window until decode overwrites them in order
            positions = (plen + jnp.arange(S_suf, dtype=i32))[None, :]
            logits, cache = mc.apply(
                {"params": params}, tokens, positions, cache,
                kv_start, jnp.broadcast_to(total, (1,)), plen,
                logit_index=slen - 1,
            )
            rng, k0 = jax.random.split(rng)
            tok0 = sample_token(k0, logits[:, -1], sampling)
            done0 = _isin(tok0, eos_ids)
            out0 = jnp.full((1, max_new), pad_id, i32).at[:, 0].set(tok0)

            def cond(c):
                step, _, _, done, _, _ = c
                return (step < max_new) & ~jnp.all(done)

            def body(c):
                step, cache, last_tok, done, out, rng = c
                # left-aligned: cache slot == sequence position
                write_index = (total + step - 1).astype(i32)
                pos = jnp.broadcast_to(write_index, (1,))[:, None]
                kv_len = jnp.broadcast_to(write_index + 1, (1,))
                logits, cache = model.apply(
                    {"params": params}, last_tok[:, None], pos, cache,
                    kv_start, kv_len, write_index,
                )
                rng, k = jax.random.split(rng)
                tok = sample_token(k, logits[:, 0], sampling)
                tok = jnp.where(done, jnp.int32(eos_ids[0]), tok)
                done = done | _isin(tok, eos_ids)
                out = out.at[:, step].set(tok)
                return (step + 1, cache, tok, done, out, rng)

            init = (jnp.int32(1), cache, tok0, done0, out0, rng)
            _, _, _, _, out, _ = jax.lax.while_loop(cond, body, init)
            return out

        return gen

    def _build_generate_prefixed(self, S_suf: int, max_new: int):
        ds = self.mesh.replicated if self.mesh is not None else None
        return (
            jax.jit(self._make_gen_prefixed(S_suf, max_new))
            .lower(
                param_avals(self.params),
                self._prefix_plane_avals(self._prefix_capacity()),
                jax.ShapeDtypeStruct((), jnp.int32, sharding=ds),
                jax.ShapeDtypeStruct((1, S_suf), jnp.int32, sharding=ds),
                jax.ShapeDtypeStruct((), jnp.int32, sharding=ds),
                jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=ds),
            )
            .compile()
        )

    def generate_prefixed(
        self,
        suffix_ids: Sequence[int],
        prefix,  # CachedPrefix
        max_new_tokens: Optional[int] = None,
        seed: Optional[int] = None,
        info: Optional[Dict] = None,  # out-param: per-request goodput share
    ) -> List[int]:
        """Generate with a device-resident cached prefix: prefill touches
        only ``suffix_ids`` (the un-cached prompt tail); the prefix KV is
        spliced from ``prefix.planes``. Raises ValueError when the suffix
        exceeds the bucket ladder (caller falls back to the cold path)."""
        pc = self.engine_config.prefix_cache
        if not suffix_ids:
            # an empty suffix would sample tok0 from a PAD token's logits
            # (logit_index clips to 0) — a silently wrong first token; every
            # real prompt has at least the per-query tail
            raise ValueError("generate_prefixed needs a non-empty suffix")
        n_suf = len(suffix_ids)
        if n_suf > max(pc.suffix_buckets):
            raise ValueError(
                f"prefixed suffix of {n_suf} tokens exceeds the largest "
                f"suffix bucket ({max(pc.suffix_buckets)})"
            )
        S_suf = bucket_len(n_suf, pc.suffix_buckets)
        max_new = (
            self.sampling.max_new_tokens if max_new_tokens is None else max_new_tokens
        )
        max_new = max(
            1, min(max_new, self.engine_config.max_seq_len
                   - max(self.engine_config.prompt_buckets))
        )
        key = (1, S_suf, max_new, ("prefix", self._prefix_capacity()))
        with self._lock:
            fn = self._compiled.get(key)
        if fn is None:
            t0 = time.perf_counter()
            fn = self._build_generate_prefixed(S_suf, max_new)
            self._record_compile(time.perf_counter() - t0)
            with self._lock:
                self._compiled.setdefault(key, fn)
                fn = self._compiled[key]
        toks = np.full((1, S_suf), self.pad_id, np.int32)
        toks[0, : len(suffix_ids)] = list(suffix_ids)
        rng = self._next_rng(seed)
        toks_j = jnp.asarray(toks)
        plen_j = jnp.int32(prefix.length)
        slen_j = jnp.int32(len(suffix_ids))
        planes = prefix.planes
        if self.mesh is not None:
            rep = self.mesh.replicated
            toks_j, plen_j, slen_j, rng = (
                jax.device_put(x, rep) for x in (toks_j, plen_j, slen_j, rng)
            )
            planes = tuple(jax.device_put(p, rep) for p in planes)
        t_call = time.perf_counter()
        out = np.asarray(fn(self.params, planes, plen_j, toks_j, slen_j, rng))
        call_s = time.perf_counter() - t_call
        eos = set(self.config.eos_token_ids)
        row: List[int] = []
        for t in out[0]:
            if int(t) in eos:
                break
            row.append(int(t))
        self._observe_generate(call_s, len(row))
        with self._lock:
            self.stats.generate_calls += 1
            self.stats.prefill_tokens += len(suffix_ids)
            self.stats.prefill_tokens_skipped += int(prefix.reused_tokens)
            self.stats.decode_tokens += len(row)
        self._record_oneshot(
            call_s, bucket=S_suf, batch=1, computed=len(suffix_ids),
            decode_tokens=len(row), decode_steps=max(len(row), 1),
            skipped=int(prefix.reused_tokens), info=info,
        )
        return row

    def warm_prefixed(
        self,
        suffix_lens: Sequence[int] = (),
        max_new_tokens: Optional[int] = None,
    ) -> None:
        """AOT-compile the prefixed generate executables for the suffix
        buckets serving will hit (compile only — the service's warmup and
        post-ingest hook call this so a cache hit never pays a compile)."""
        if self.prefix_cache is None:
            return
        pc = self.engine_config.prefix_cache
        max_new = (
            self.sampling.max_new_tokens if max_new_tokens is None else max_new_tokens
        )
        max_new = max(
            1, min(max_new, self.engine_config.max_seq_len
                   - max(self.engine_config.prompt_buckets))
        )
        buckets = {
            bucket_len(min(max(n, 1), max(pc.suffix_buckets)), pc.suffix_buckets)
            for n in (suffix_lens or (self.RAG_TAIL_BUCKET,))
        }
        for S_suf in sorted(buckets):
            key = (1, S_suf, max_new, ("prefix", self._prefix_capacity()))
            with self._lock:
                built = key in self._compiled
            if not built:
                t0 = time.perf_counter()
                fn = self._build_generate_prefixed(S_suf, max_new)
                self._record_compile(time.perf_counter() - t0)
                with self._lock:
                    self._compiled.setdefault(key, fn)

    def _get_compiled(
        self, B: int, S: int, max_new: int, chunk: Optional[int] = None
    ) -> jax.stages.Compiled:
        key = (B, S, max_new, chunk)
        with self._lock:
            fn = self._compiled.get(key)
        if fn is None:
            t0 = time.perf_counter()
            if chunk == "spec":
                fn = self._build_generate_spec(S, max_new)
            else:
                fn = self._build_generate(B, S, max_new, chunk)
            self._record_compile(time.perf_counter() - t0)
            with self._lock:
                self._compiled.setdefault(key, fn)
                fn = self._compiled[key]
        return fn

    _SPEC_EMA_DECAY = 0.7
    _SPEC_REPROBE = 32
    # single-fetch RAG prompt-tail bucket ("\n\nUser: {q}\n\nChatbot:"
    # padded) — fixed so the executable set is one per store shape
    RAG_TAIL_BUCKET = 128

    def _spec_applicable(self, n_prompts: int, chunk) -> bool:
        """Prompt-lookup speculation serves the batch-1 single-shot case —
        greedy (token-identical) and sampled (distribution-identical via
        rejection sampling); batch > 1 and chunked prompts fall back to the
        vanilla loop. Under ``speculative="auto"`` the engine additionally
        disables itself when MEASURED acceptance stays below
        ``spec_min_accept`` tokens/verify (a k+1-wide verify forward costs
        ~1.4 decode steps measured at the 8B int8 flagship point — below
        that, lookup is not paying for itself), re-probing every
        ``_SPEC_REPROBE``-th eligible call in case the workload changed."""
        mode = self.engine_config.speculative
        if mode not in ("prompt_lookup", "auto") or n_prompts != 1 or chunk is not None:
            return False
        if mode == "auto":
            with self._lock:
                ema, skips = self._spec_ema, self._spec_skips
                low = ema is not None and ema < self.engine_config.spec_min_accept
                if low:
                    self._spec_skips += 1
            if low and (skips + 1) % self._SPEC_REPROBE != 0:
                return False
        return True

    def _spec_record(self, emitted: int, iters: int):
        """Fold one speculative call's measured acceptance into the EMA."""
        acc = emitted / max(iters, 1)
        with self._lock:
            self.stats.spec_verify_steps += iters
            self.stats.spec_emitted_tokens += emitted
            d = self._SPEC_EMA_DECAY
            self._spec_ema = acc if self._spec_ema is None else d * self._spec_ema + (1 - d) * acc

    # ------------------------------------------------------------------
    # host-side API
    # ------------------------------------------------------------------
    def _bucket_len(self, n: int) -> int:
        return bucket_len(n, self.engine_config.prompt_buckets)

    @staticmethod
    def _bucket_batch(n: int) -> int:
        return next_pow2(n)

    def _clamp_max_new(self, S: int, max_new: int) -> int:
        """Keep S + max_new within the engine's cache budget."""
        budget = self.engine_config.max_seq_len - S
        return max(1, min(max_new, budget))

    def _next_rng(self, seed: Optional[int]) -> jax.Array:
        """Fresh randomness per call unless the caller pins a seed."""
        if seed is not None:
            return jax.random.PRNGKey(seed)
        with self._lock:
            self._rng_counter += 1
            counter = self._rng_counter
        return jax.random.fold_in(jax.random.PRNGKey(self.sampling.seed), counter)

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
        seed: Optional[int] = None,
        info: Optional[Dict] = None,  # out-param: per-request goodput share
    ) -> List[List[int]]:
        """Generate continuations for a batch of token-id prompts.

        Returns one token list per prompt, truncated at (and excluding) EOS.
        Batches larger than ``EngineConfig.max_batch_size`` split into
        sequential sub-batches (order preserved).
        """
        if not prompts:
            return []
        faults.maybe_fail("generate")
        max_new = (
            self.sampling.max_new_tokens if max_new_tokens is None else max_new_tokens
        )
        if max_new <= 0:
            return [[] for _ in prompts]

        cap = self.engine_config.max_batch_size
        if len(prompts) > cap:
            # one base key, folded per sub-batch: a pinned seed stays
            # reproducible without every sub-batch sampling identically
            base = self._next_rng(seed)
            out: List[List[int]] = []
            for sub, i in enumerate(range(0, len(prompts), cap)):
                out.extend(
                    self._generate_batch(
                        prompts[i : i + cap], max_new,
                        jax.random.fold_in(base, sub), info=info,
                    )
                )
            return out
        return self._generate_batch(
            prompts, max_new, self._next_rng(seed), info=info
        )

    def _generate_batch(
        self,
        prompts: Sequence[Sequence[int]],
        max_new: int,
        rng: jax.Array,
        info: Optional[Dict] = None,
    ) -> List[List[int]]:
        """One device call for <= max_batch_size prompts with a decided rng."""
        maxlen = max(len(p) for p in prompts)
        largest = max(self.engine_config.prompt_buckets)
        cap = self.engine_config.max_chunked_prompt
        if maxlen > cap:
            # the ONLY truncation in the engine — and a loud one
            logger.warning(
                "prompt of %d tokens exceeds max_chunked_prompt=%d; "
                "left-truncating to the most recent %d tokens",
                maxlen, cap, cap,
            )
            maxlen = cap
        if maxlen <= largest:
            S = self._bucket_len(maxlen)
            chunk = None
            max_new = self._clamp_max_new(S, max_new)
        else:
            # chunked prefill: pad to a multiple of the largest bucket and
            # run the prompt through the cache chunk by chunk — no silent
            # truncation. Decode keeps the same room the largest single-shot
            # bucket gets (max_seq_len - largest), bounding cache HBM at
            # T = S + that budget even for adversarial max_new_tokens.
            chunk = largest
            S = -(-maxlen // chunk) * chunk
            budget = max(1, self.engine_config.max_seq_len - largest)
            max_new = max(1, min(max_new, budget))
        B = self._bucket_batch(len(prompts))

        tokens = np.full((B, S), self.pad_id, np.int32)
        pad_mask = np.zeros((B, S), np.int32)
        for i, p in enumerate(prompts):
            p = list(p)[-maxlen:]  # no-op below the cap (maxlen = max row len)
            tokens[i, S - len(p):] = p
            pad_mask[i, S - len(p):] = 1
        # empty rows (batch padding) get one BOS so real_len >= 1
        for i in range(len(prompts), B):
            tokens[i, -1] = self.config.bos_token_id
            pad_mask[i, -1] = 1

        spec = self._spec_applicable(len(prompts), chunk)
        fn = self._get_compiled(B, S, max_new, "spec" if spec else chunk)
        tokens_j, mask_j, rng_j = self._place_inputs(tokens, pad_mask, rng)
        iters = 0
        t_call = time.perf_counter()
        if spec:
            out = np.asarray(fn(self.params, tokens_j, mask_j, rng_j))  # ONE fetch
            iters = int(out[0, max_new])  # packed in the slack slot
            out = out[:, :max_new]
        else:
            out = np.asarray(fn(self.params, tokens_j, mask_j, rng_j))
        call_s = time.perf_counter() - t_call

        results: List[List[int]] = []
        eos = set(self.config.eos_token_ids)
        n_decode = 0
        for i in range(len(prompts)):
            row = []
            for t in out[i]:
                if int(t) in eos:
                    break
                row.append(int(t))
            results.append(row)
            n_decode += len(row)
        spec_accept = None
        if spec and int(iters) > 0:
            # tokens the VERIFY forwards emitted: answer tokens + the EOS
            # that ended it (if any) MINUS tok0 (sampled at prefill, not by
            # a verify); measured acceptance feeds the auto mode and the
            # /metrics counters
            emitted = len(results[0]) + (1 if len(results[0]) < max_new else 0) - 1
            self._spec_record(max(emitted, 0), int(iters))
            spec_accept = round(max(emitted, 0) / int(iters), 4)
        self._observe_generate(call_s, max((len(r) for r in results), default=1))
        with self._lock:
            self.stats.generate_calls += 1
            self.stats.prefill_tokens += int(pad_mask.sum())
            self.stats.decode_tokens += n_decode
        self._record_oneshot(
            call_s, bucket=S, batch=B, computed=int(pad_mask.sum()),
            decode_tokens=n_decode,
            decode_steps=max((len(r) for r in results), default=1),
            info=info,
        )
        if info is not None and spec_accept is not None and self.ledger.enabled:
            # one-shot speculation: the device-side matcher folds draft
            # outcomes into emitted/iters — the per-call acceptance mean
            # is the only per-request figure it can expose. Gated on the
            # ledger like every other goodput key: TPU_RAG_GOODPUT=0
            # means NO goodput block in info, not a partial one
            info.setdefault("goodput", {})["spec_accept_len_mean"] = spec_accept
        if info is not None and spec and int(iters) > 0:
            # approximation fingerprint (obs/shadow.py): speculation ran
            # for this request — byte-identical by contract, and exactly
            # what the shadow auditor exists to verify on live traffic
            ap = info.setdefault("approx", [])
            if "spec_verify" not in ap:
                ap.append("spec_verify")
        return results

    def _place_inputs(self, tokens: np.ndarray, pad_mask: np.ndarray, rng: jax.Array):
        """Match the shardings the executable was lowered with."""
        if self.mesh is None:
            return jnp.asarray(tokens), jnp.asarray(pad_mask), rng
        rep = self.mesh.replicated
        return (
            jax.device_put(jnp.asarray(tokens), rep),
            jax.device_put(jnp.asarray(pad_mask), rep),
            jax.device_put(rng, rep),
        )

    def warmup(
        self,
        batch_sizes: Sequence[int] = (1,),
        buckets: Optional[Sequence[int]] = None,
        max_new_tokens: Optional[int] = None,
    ):
        """AOT-compile the executables requests will hit — compile time only,
        nothing executes (readiness gating, survey §5 failure-detection note)."""
        buckets = buckets or self.engine_config.prompt_buckets
        max_new = max_new_tokens or self.sampling.max_new_tokens
        for b in batch_sizes:
            for s in buckets:
                mb = self._bucket_batch(b)
                mn = self._clamp_max_new(s, max_new)
                # STATIC config decides what to warm — never the runtime
                # acceptance EMA (_spec_applicable), which would skip the
                # spec compile on a re-warm after a low-acceptance phase and
                # push the full AOT compile into the next reprobed request
                spec_mode = self.engine_config.speculative
                if mb == 1 and spec_mode in ("prompt_lookup", "auto"):
                    self._get_compiled(1, s, mn, "spec")
                    if spec_mode == "auto":
                        # auto can fall back to the vanilla loop on measured
                        # low acceptance — warm that executable too
                        self._get_compiled(1, s, mn)
                else:
                    self._get_compiled(mb, s, mn)
