"""Serving engine: XLA-compiled prefill + KV-cached decode, sampling, batching."""

from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.engine.sampling import sample_token

__all__ = ["InferenceEngine", "sample_token"]
