"""Training step: causal-LM loss + optax update, shardable over the mesh.

The reference has no training of any kind (survey §5 checkpoint note: its only
persisted state is weights/index). The framework still ships a real training
path — fine-tuning the served model on the indexed corpus is the natural
extension, and the multi-chip dry-run exercises exactly this step end-to-end
(tp×dp sharded params, dp-sharded batch, XLA-inserted gradient psums).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from rag_llm_k8s_tpu.core.config import DTypePolicy, LlamaConfig
from rag_llm_k8s_tpu.models.llama import LlamaModel, make_kv_cache, mask_window


def lm_loss(
    model: LlamaModel,
    params,
    tokens: jax.Array,  # [B, S]
    mask: jax.Array,  # [B, S] 1 = real token (contiguous run, e.g. right-pad)
) -> jax.Array:
    """Next-token cross entropy, fp32, masked mean."""
    B, S = tokens.shape
    cache = make_kv_cache(model.config, B, S, model.dtypes.compute_dtype)
    kv_start, kv_len = mask_window(mask)
    positions = jnp.clip(jnp.cumsum(mask, axis=-1) - 1, 0)
    logits, _ = model.apply(
        {"params": params}, tokens, positions, cache, kv_start, kv_len, jnp.int32(0)
    )
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    targets = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    w = (mask[:, :-1] * mask[:, 1:]).astype(jnp.float32)
    return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)


def make_train_step(
    config: LlamaConfig,
    dtypes: DTypePolicy = DTypePolicy(),
    optimizer: Optional[optax.GradientTransformation] = None,
    mesh=None,
):
    """Returns ``(init_opt_state, train_step)``; ``train_step`` is jittable and
    sharding-transparent: with TP/DP-placed params and dp-sharded batches, XLA
    emits the ICI collectives (grad psum over dp, activation collectives over
    tp) — no pmap, no hand-written comms. Pass the ``jax.sharding.Mesh`` to
    enable sequence parallelism: with ``sp > 1`` in the mesh, attention runs
    as the differentiable ring over the sp axis (sequences shard across
    devices; K/V blocks rotate on the ICI ring)."""
    # "xla" attention: the dense-einsum path is the differentiable one (the
    # Pallas kernels are inference-only, no custom VJP)
    model = LlamaModel(config, dtypes, attn_impl="xla", mesh=mesh)
    opt = optimizer or optax.adamw(1e-5)

    def init_opt_state(params):
        return opt.init(params)

    def train_step(params, opt_state, tokens, mask):
        loss, grads = jax.value_and_grad(lambda p: lm_loss(model, p, tokens, mask))(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return init_opt_state, train_step
