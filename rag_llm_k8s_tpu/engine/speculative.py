"""Scheduler-side drafting for paged speculative decoding (host half).

The paged continuous engine's draft-and-verify loop splits cleanly in two:
the DEVICE half is one multi-token verify executable per sync window
(``ContinuousEngine._build_verify_paged`` — K+1 fed tokens per row through
the block tables, K+1 logit planes back, target-matching acceptance inside
the program), and the HOST half — this module — decides *what* to draft
between windows:

- :func:`prompt_lookup_draft` — the draft source. RAG-grounded answers
  heavily copy their retrieved context (SIFT's observation; the one-shot
  engine's device-side matcher exploits the same structure), so the
  request's own token history — assembled prompt (head + retrieved
  chunks) + everything emitted so far — IS the draft corpus: propose the
  tokens that followed the most recent earlier occurrence of the trailing
  ``ngram``-gram. No draft model, no extra weights in HBM, no second
  forward — drafting is a numpy scan over a few KB of host ints.
- :func:`adaptive_draft_len` / :func:`fold_acceptance` — the per-row
  adaptive-K controller. Every verify window folds each row's measured
  acceptance fraction (accepted / offered) into a decayed per-row EMA;
  the next window's draft length scales with it, so a row whose output
  does NOT quote its context degrades gracefully to K=1 (a 2-wide verify
  costs ~one decode step — decode is weight-bandwidth-bound, width is
  nearly free) instead of paying a wide verify that rejects everything.

Correctness lives entirely in the verify step's acceptance rule
(``engine/sampling.py``: accept while the draft equals the model's OWN
(seed, position)-keyed target), so nothing here can change what a request
emits — a wrong draft costs latency, never bytes. docs/SPECULATIVE.md.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = [
    "SPEC_EMA_DECAY",
    "adaptive_draft_len",
    "fold_acceptance",
    "prompt_lookup_draft",
]

#: Per-row acceptance EMA decay: ~5-window memory. Short on purpose — a
#: RAG answer often alternates between quoting spans (high acceptance) and
#: free-form connective text (low); a long memory would hold K low through
#: an entire quoted span.
SPEC_EMA_DECAY = 0.8


def prompt_lookup_draft(
    history: Sequence[int], ngram: int, k: int
) -> List[int]:
    """Up to ``k`` draft tokens for a row whose token history (assembled
    prompt + emitted) is ``history``: the continuation of the most recent
    EARLIER occurrence of the trailing ``ngram``-gram, ``[]`` when the
    gram never repeats (the row then takes a plain decode step inside the
    verify window — zero drafts is the graceful floor).

    Host mirror of the one-shot engine's device matcher
    (``InferenceEngine._make_gen_spec``): same last-occurrence rule, same
    gram size (``EngineConfig.spec_ngram``); here the scan is a couple of
    vectorized numpy passes per row per window instead of device lanes.
    A continuation is truncated at the frontier rather than rejected —
    a short draft still saves its accepted length."""
    n = len(history)
    if k <= 0 or ngram <= 0 or n < ngram + 1:
        return []
    h = np.asarray(history, dtype=np.int64)
    tail = h[-ngram:]
    # candidate END positions j in [0, n-2]: the gram occupies
    # [j-ngram+1, j] and must end strictly before the frontier gram (an
    # occurrence ending at n-1 is the frontier matching itself — its
    # continuation is unwritten future, the one-shot matcher's pad trap)
    ok = np.ones(n - 1, dtype=bool)
    for i in range(ngram):
        col = np.empty(n - 1, dtype=np.int64)
        col[:i] = -1  # j < i cannot hold a full gram
        if i:
            col[i:] = h[: n - 1 - i]
        else:
            col[:] = h[: n - 1]
        ok &= col == tail[ngram - 1 - i]
    idx = np.nonzero(ok)[0]
    if idx.size == 0:
        return []
    j = int(idx[-1])
    return [int(t) for t in h[j + 1 : j + 1 + k]]


def adaptive_draft_len(
    ema: Optional[float], k_max: int, min_accept: float
) -> int:
    """This window's draft length for a row with acceptance EMA ``ema``:

    - no evidence yet (``None``) → the full ``k_max`` (optimistic start —
      the first window measures; a grounded answer's quoting shows up
      immediately);
    - EMA below ``min_accept`` → 1 (the graceful floor: one drafted token
      keeps the row probing at ~zero cost, so a row that STARTS quoting
      again recovers within a few windows);
    - otherwise → ``round(ema * k_max)``, clamped to ``[1, k_max]`` — the
      draft length tracks how much of the last windows' drafts survived.
    """
    if k_max < 1:
        return 0
    if ema is None:
        return k_max
    if ema < min_accept:
        return 1
    return max(1, min(k_max, int(round(ema * k_max))))


def fold_acceptance(
    ema: Optional[float], offered: int, accepted: int
) -> Optional[float]:
    """Fold one verify window's measured acceptance fraction into a row's
    decayed EMA (identity when the window offered nothing — a no-match
    window is no evidence about acceptance)."""
    if offered <= 0:
        return ema
    r = accepted / offered
    if ema is None:
        return r
    return SPEC_EMA_DECAY * ema + (1.0 - SPEC_EMA_DECAY) * r
