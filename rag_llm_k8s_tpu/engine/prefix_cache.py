"""Cross-request device-resident KV prefix cache.

Every /generate request re-prefills the same fixed prompt head (BOS + system
message + "\\n\\nContext: "), and popular queries re-prefill the same
retrieved chunks — even though the engine already supports chunked prefill
with offset causality over a populated cache prefix. This module keeps those
shared segments' KV **on device** and splices them into each request's fresh
cache via ``dynamic_update_slice``, so prefill starts at the first non-shared
token (HA-RAG / SIFT: KV reuse for shared RAG prompt segments is the dominant
prefill optimization for retrieve-then-generate serving).

Anatomy:

- **Segment blocks** (``_Entry``): per-segment KV ``[L, 1, K, Sb, hd]``
  (+ fp32 scale planes under int8-KV), padded to a bucketed length ``Sb``,
  held in an HBM-budgeted LRU keyed by ``(segment_key, position_slot)``.
  RoPE makes K position-dependent, so a block is reusable only at the exact
  token offset (*slot*) it was computed at; under the default ``reuse=
  "exact"`` policy the key additionally carries the chain of segment keys
  that preceded it — K/V of layers > 0 attend over the left context, so an
  exact-chain match is what makes cached-vs-cold logits IDENTICAL (the
  parity contract tests/test_prefix_cache.py pins). ``reuse="slot"`` relaxes
  to offset-only matching (HA-RAG-style hotness reuse: an approximation
  those systems accept for the prefill savings).
- **Assembled buffers**: the fully spliced ``[L, 1, K, P, hd]`` prefix a
  request hands to ``InferenceEngine.generate_prefixed``, memoized per
  segment chain so a repeated query re-splices nothing — its whole prefix
  is one device handle and prefill touches only the per-query tail.
- **Miss path**: the first request for a segment builds its block with the
  engine's AOT segment-prefill executable (the same chunked-prefill model
  the long-prompt path uses) — prefill work equivalent to the cold path,
  plus the slice/splice — and every later slot-matched request skips it.

The cache never changes executable shapes: prefix/suffix lengths are dynamic
scalars inside a fixed ``(P, suffix_bucket, max_new)`` executable, so a new
hit pattern never triggers an AOT compile.
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)


@dataclass
class CachedPrefix:
    """A resolved, device-resident prompt prefix ready to splice.

    ``planes`` is the KV tuple ``(k, v)`` — or ``(k, v, k_scale, v_scale)``
    under int8-KV — each ``[L, 1, K, P, hd]`` (scales ``[L, 1, K, P]``),
    with real content in slots ``[0, length)`` and don't-care beyond (the
    consumer's kv windows never reach it). Consumed by
    ``InferenceEngine.generate_prefixed`` and
    ``ContinuousEngine.admit_prefixed``.
    """

    planes: Tuple
    length: int  # real prefix tokens covered
    capacity: int  # P — the static splice-buffer width
    reused_tokens: int  # tokens whose KV came from cache hits
    computed_tokens: int  # tokens prefilled (cache misses) to build this
    # stable identity of the prefix CONTENT (the segment-key chain + total
    # length), set only under exact-chain reuse: the paged continuous
    # engine keys its block-granular sharing on it — two requests with the
    # same chain_key map the same physical pool blocks copy-free
    # (ref-counted; ContinuousEngine._admit_prefixed_paged). None under
    # "slot" reuse, whose approximate blocks are NOT content-identical.
    chain_key: Optional[Tuple] = None


@dataclass
class _Entry:
    planes: Tuple  # [L, 1, K, Sb, hd] (+ scale planes) device arrays
    seg_len: int  # real tokens (<= bucket)
    nbytes: int
    pinned: bool = False


def _planes_nbytes(planes: Tuple) -> int:
    return int(sum(int(p.nbytes) for p in planes))


class PrefixCache:
    """HBM-budgeted LRU of segment KV blocks + assembled prefix buffers.

    Thread-safe; device work (build/splice) runs outside the lock — entries
    and buffers are immutable device arrays, so concurrent readers never see
    a partially written block.
    """

    def __init__(self, config, engine):
        if config.reuse not in ("exact", "slot"):
            raise ValueError(
                f"prefix_cache.reuse={config.reuse!r}: expected 'exact' or 'slot'"
            )
        self.config = config
        self.engine = engine  # owning InferenceEngine (builds the blocks)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._assembled: "OrderedDict[tuple, Tuple[Tuple, int]]" = OrderedDict()
        self._pinned_keys: set = set()
        self.entry_bytes = 0
        self.assembled_bytes = 0
        # counters (read by /metrics and bench.py)
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.tokens_computed = 0

    # -- keys -----------------------------------------------------------
    def _entry_key(self, seg_key: str, offset: int, chain: Tuple[str, ...]):
        if self.config.reuse == "slot":
            return (seg_key, offset)
        return (seg_key, offset, chain)

    def pin(self, seg_key: str) -> None:
        """Mark a segment key (e.g. the fixed prompt head) never-evicted."""
        with self._lock:
            self._pinned_keys.add(seg_key)
            for k, e in self._entries.items():
                if k[0] == seg_key:
                    e.pinned = True

    # -- stats ----------------------------------------------------------
    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {
                "prefix_cache_hits": self.hits,
                "prefix_cache_misses": self.misses,
                "prefill_tokens_skipped": self.tokens_reused,
                "prefix_cache_entries": len(self._entries),
                # TOTAL device bytes held: segment blocks + the assembled
                # full-prefix memo buffers (both count against the budget)
                "prefix_cache_bytes": self.entry_bytes + self.assembled_bytes,
            }

    def bytes_by_device(self) -> Dict[int, int]:
        """Resident cache bytes attributed per device id (segment blocks +
        assembled buffers) — the per-device scrape view
        (``rag_prefix_cache_device_bytes``, obs/devices.py). A plane sharded
        over several devices splits its bytes evenly across them; planes
        without a ``devices()`` API (CPU test doubles) attribute to device
        0. Reads only host-side handles — no device sync."""
        out: Dict[int, int] = {}

        def _attribute(planes: Tuple) -> None:
            for p in planes:
                nbytes = int(getattr(p, "nbytes", 0))
                try:
                    devs = list(p.devices())
                except Exception:  # noqa: BLE001 — non-jax arrays: device 0
                    devs = []
                if not devs:
                    out[0] = out.get(0, 0) + nbytes
                    continue
                share = nbytes // len(devs)
                for d in devs:
                    did = int(getattr(d, "id", 0))
                    out[did] = out.get(did, 0) + share

        with self._lock:
            entries = [e.planes for e in self._entries.values()]
            buffers = [buf for buf, _ in self._assembled.values()]
        for planes in entries:
            _attribute(planes)
        for planes in buffers:
            _attribute(planes)
        return out

    # -- the one public resolve/populate entry point ---------------------
    def prefix_for(self, segments: Sequence[Tuple[str, Sequence[int]]]
                   ) -> Optional[CachedPrefix]:
        """Resolve an ordered segment list ``[(key, token_ids), ...]`` into a
        spliced prefix buffer, building (and caching) any missing blocks —
        the miss path IS the populate path, so prefill work is never done
        twice for a slot-matched segment. Returns None when the prefix can't
        be represented (over the buffer capacity, or a single segment over
        the largest segment bucket) — the caller falls back to cold prefill.
        """
        total = sum(len(ids) for _, ids in segments)
        P = self.config.max_prefix_tokens
        if total == 0 or total > P:
            return None
        max_seg = max(self.config.segment_buckets)
        if any(len(ids) > max_seg for _, ids in segments):
            return None

        chain_full = tuple(k for k, _ in segments)
        akey = (chain_full, total)
        with self._lock:
            memo = self._assembled.get(akey)
            if memo is not None:
                self._assembled.move_to_end(akey)
                # touch member entries so the LRU order tracks real use
                off, chain = 0, ()
                for key, ids in segments:
                    ek = self._entry_key(key, off, chain)
                    if ek in self._entries:
                        self._entries.move_to_end(ek)
                    off += len(ids)
                    chain = chain + (key,)
                self.hits += len(segments)
                self.tokens_reused += total
                return CachedPrefix(
                    memo[0], memo[1], P, total, 0,
                    chain_key=akey if self.config.reuse == "exact" else None,
                )

        buf = self.engine.prefix_buffer_zero()
        off = 0
        chain: Tuple[str, ...] = ()
        reused = computed = n_hit = n_miss = 0
        for key, ids in segments:
            seg_len = len(ids)
            ek = self._entry_key(key, off, chain)
            with self._lock:
                e = self._entries.get(ek)
                if e is not None and e.seg_len == seg_len:
                    self._entries.move_to_end(ek)
                else:
                    e = None  # slot/length mismatch: treat as a miss
            if e is None:
                # build with the true left context (buf holds chain's KV):
                # under "exact" reuse this makes the block bit-faithful to
                # what a cold prefill would have computed at these slots
                planes = self.engine.build_segment_kv(list(ids), buf, off)
                e = _Entry(
                    planes=planes, seg_len=seg_len,
                    nbytes=_planes_nbytes(planes),
                    pinned=key in self._pinned_keys,
                )
                self._insert(ek, e)
                n_miss += 1
                computed += seg_len
            else:
                n_hit += 1
                reused += seg_len
            buf = self.engine.splice_prefix(buf, e.planes, off)
            off += seg_len
            chain = chain + (key,)

        buf_bytes = _planes_nbytes(buf)
        with self._lock:
            self.hits += n_hit
            self.misses += n_miss
            self.tokens_reused += reused
            self.tokens_computed += computed
            # two threads can resolve the same chain concurrently (both miss
            # the memo check): drop the loser's bytes before re-assigning or
            # assembled_bytes would over-count forever
            prev = self._assembled.pop(akey, None)
            if prev is not None:
                self.assembled_bytes -= _planes_nbytes(prev[0])
            self._assembled[akey] = (buf, off)
            self.assembled_bytes += buf_bytes
            # assembled buffers are full-capacity (P-wide) planes — at 8B
            # defaults ~512 MiB EACH — so they share the ONE HBM budget with
            # the segment blocks and, being pure re-splice avoidance, evict
            # FIRST (oldest chain first; the buffer just added is kept so a
            # repeat of this very query still skips its splices)
            budget = int(self.config.hbm_budget_mb) * (1 << 20)
            cap = max(1, int(self.config.assembled_cache_entries))
            for k in list(self._assembled):
                if (
                    len(self._assembled) <= cap
                    and self.entry_bytes + self.assembled_bytes <= budget
                ):
                    break
                if k == akey:
                    continue
                old_buf, _ = self._assembled.pop(k)
                self.assembled_bytes -= _planes_nbytes(old_buf)
        return CachedPrefix(
            buf, off, P, reused, computed,
            chain_key=akey if self.config.reuse == "exact" else None,
        )

    # -- LRU bookkeeping -------------------------------------------------
    def _insert(self, key, entry: _Entry) -> None:
        budget = int(self.config.hbm_budget_mb) * (1 << 20)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.entry_bytes -= old.nbytes
            self._entries[key] = entry
            self.entry_bytes += entry.nbytes
            # assembled buffers (pure re-splice avoidance) evict before any
            # segment block does — a block eviction costs a real re-prefill
            while (
                self._assembled
                and self.entry_bytes + self.assembled_bytes > budget
            ):
                _, (old_buf, _) = self._assembled.popitem(last=False)
                self.assembled_bytes -= _planes_nbytes(old_buf)
            # then evict LRU-first until under budget; pinned blocks (the
            # head — reused by 100% of requests) are skipped, and the entry
            # just inserted is never its own eviction victim
            for k in list(self._entries):
                if self.entry_bytes <= budget:
                    break
                if k == key or self._entries[k].pinned:
                    continue
                victim = self._entries.pop(k)
                self.entry_bytes -= victim.nbytes
                logger.debug("prefix cache evicted %r (%d bytes)", k, victim.nbytes)

    def clear(self) -> None:
        """Drop every cached block and assembled buffer (frees the HBM)."""
        with self._lock:
            self._entries.clear()
            self._assembled.clear()
            self.entry_bytes = 0
            self.assembled_bytes = 0
