"""Cross-request device-resident KV prefix cache.

Every /generate request re-prefills the same fixed prompt head (BOS + system
message + "\\n\\nContext: "), and popular queries re-prefill the same
retrieved chunks — even though the engine already supports chunked prefill
with offset causality over a populated cache prefix. This module keeps those
shared segments' KV **on device** and splices them into each request's fresh
cache via ``dynamic_update_slice``, so prefill starts at the first non-shared
token (HA-RAG / SIFT: KV reuse for shared RAG prompt segments is the dominant
prefill optimization for retrieve-then-generate serving).

Anatomy:

- **Segment blocks** (``_Entry``): per-segment KV ``[L, 1, K, Sb, hd]``
  (+ fp32 scale planes under int8-KV), padded to a bucketed length ``Sb``,
  held in an HBM-budgeted LRU keyed by ``(segment_key, position_slot)``.
  RoPE makes K position-dependent, so a block is reusable only at the exact
  token offset (*slot*) it was computed at; under the default ``reuse=
  "exact"`` policy the key additionally carries the chain of segment keys
  that preceded it — K/V of layers > 0 attend over the left context, so an
  exact-chain match is what makes cached-vs-cold logits IDENTICAL (the
  parity contract tests/test_prefix_cache.py pins). ``reuse="slot"`` relaxes
  to offset-only matching (HA-RAG-style hotness reuse: an approximation
  those systems accept for the prefill savings).
- **Assembled buffers**: the fully spliced ``[L, 1, K, P, hd]`` prefix a
  request hands to ``InferenceEngine.generate_prefixed``, memoized per
  segment chain so a repeated query re-splices nothing — its whole prefix
  is one device handle and prefill touches only the per-query tail.
- **Miss path**: the first request for a segment builds its block with the
  engine's AOT segment-prefill executable (the same chunked-prefill model
  the long-prompt path uses) — prefill work equivalent to the cold path,
  plus the slice/splice — and every later slot-matched request skips it.

The cache never changes executable shapes: prefix/suffix lengths are dynamic
scalars inside a fixed ``(P, suffix_bucket, max_new)`` executable, so a new
hit pattern never triggers an AOT compile.
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)


@dataclass
class CachedPrefix:
    """A resolved, device-resident prompt prefix ready to splice.

    ``planes`` is the KV tuple ``(k, v)`` — or ``(k, v, k_scale, v_scale)``
    under int8-KV — each ``[L, 1, K, P, hd]`` (scales ``[L, 1, K, P]``),
    with real content in slots ``[0, length)`` and don't-care beyond (the
    consumer's kv windows never reach it). Consumed by
    ``InferenceEngine.generate_prefixed`` and
    ``ContinuousEngine.admit_prefixed``.
    """

    planes: Tuple
    length: int  # real prefix tokens covered
    capacity: int  # P — the static splice-buffer width
    reused_tokens: int  # tokens whose KV came from cache hits
    computed_tokens: int  # tokens prefilled (cache misses) to build this
    # stable identity of the prefix CONTENT (the segment-key chain + total
    # length), set only under exact-chain reuse: the paged continuous
    # engine keys its block-granular sharing on it — two requests with the
    # same chain_key map the same physical pool blocks copy-free
    # (ref-counted; ContinuousEngine._admit_prefixed_paged). None under
    # "slot" reuse, whose approximate blocks are NOT content-identical.
    chain_key: Optional[Tuple] = None


@dataclass
class _Entry:
    planes: Tuple  # [L, 1, K, Sb, hd] (+ scale planes) device arrays
    seg_len: int  # real tokens (<= bucket)
    nbytes: int
    pinned: bool = False
    # consumptions since creation (every resolve that HITS this entry bumps
    # it) — lookahead staging records the creation-time value so a stale
    # speculation releases ONLY blocks nothing else touched in between
    uses: int = 0
    # creation stamp (monotonic per cache, set by _insert): staging records
    # it so a stale release never drops a DIFFERENT entry rebuilt at the
    # same key after the staged one was budget-evicted (a fresh rebuild
    # also starts at uses=0 — the use counter alone can't tell them apart)
    stamp: int = 0


def _planes_nbytes(planes: Tuple) -> int:
    return int(sum(int(p.nbytes) for p in planes))


class PrefixCache:
    """HBM-budgeted LRU of segment KV blocks + assembled prefix buffers.

    Thread-safe; device work (build/splice) runs outside the lock — entries
    and buffers are immutable device arrays, so concurrent readers never see
    a partially written block.
    """

    def __init__(self, config, engine):
        if config.reuse not in ("exact", "slot"):
            raise ValueError(
                f"prefix_cache.reuse={config.reuse!r}: expected 'exact' or 'slot'"
            )
        self.config = config
        self.engine = engine  # owning InferenceEngine (builds the blocks)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._assembled: "OrderedDict[tuple, Tuple[Tuple, int]]" = OrderedDict()
        # consumptions per assembled buffer since creation (keys ⊆
        # _assembled) — same stale-release discipline as _Entry.uses
        self._assembled_uses: Dict[tuple, int] = {}
        # creation stamps for assembled buffers (keys ⊆ _assembled) — same
        # identity discipline as _Entry.stamp
        self._assembled_stamp: Dict[tuple, int] = {}
        self._creation_seq = 0  # feeds both stamp tables
        self._pinned_keys: set = set()
        self.entry_bytes = 0
        self.assembled_bytes = 0
        # counters (read by /metrics and bench.py)
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.tokens_computed = 0

    # -- keys -----------------------------------------------------------
    def _entry_key(self, seg_key: str, offset: int, chain: Tuple[str, ...]):
        if self.config.reuse == "slot":
            return (seg_key, offset)
        return (seg_key, offset, chain)

    def pin(self, seg_key: str) -> None:
        """Mark a segment key (e.g. the fixed prompt head) never-evicted."""
        with self._lock:
            self._pinned_keys.add(seg_key)
            for k, e in self._entries.items():
                if k[0] == seg_key:
                    e.pinned = True

    # -- stats ----------------------------------------------------------
    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {
                "prefix_cache_hits": self.hits,
                "prefix_cache_misses": self.misses,
                "prefill_tokens_skipped": self.tokens_reused,
                "prefix_cache_entries": len(self._entries),
                # TOTAL device bytes held: segment blocks + the assembled
                # full-prefix memo buffers (both count against the budget)
                "prefix_cache_bytes": self.entry_bytes + self.assembled_bytes,
            }

    def bytes_by_device(self) -> Dict[int, int]:
        """Resident cache bytes attributed per device id (segment blocks +
        assembled buffers) — the per-device scrape view
        (``rag_prefix_cache_device_bytes``, obs/devices.py). A plane sharded
        over several devices splits its bytes evenly across them; planes
        without a ``devices()`` API (CPU test doubles) attribute to device
        0. Reads only host-side handles — no device sync."""
        out: Dict[int, int] = {}

        def _attribute(planes: Tuple) -> None:
            for p in planes:
                nbytes = int(getattr(p, "nbytes", 0))
                try:
                    devs = list(p.devices())
                except Exception:  # noqa: BLE001 — non-jax arrays: device 0
                    devs = []
                if not devs:
                    out[0] = out.get(0, 0) + nbytes
                    continue
                share = nbytes // len(devs)
                for d in devs:
                    did = int(getattr(d, "id", 0))
                    out[did] = out.get(did, 0) + share

        with self._lock:
            entries = [e.planes for e in self._entries.values()]
            buffers = [buf for buf, _ in self._assembled.values()]
        for planes in entries:
            _attribute(planes)
        for planes in buffers:
            _attribute(planes)
        return out

    # -- the one public resolve/populate entry point ---------------------
    def prefix_for(self, segments: Sequence[Tuple[str, Sequence[int]]],
                   _staged: Optional[Dict] = None) -> Optional[CachedPrefix]:
        """Resolve an ordered segment list ``[(key, token_ids), ...]`` into a
        spliced prefix buffer, building (and caching) any missing blocks —
        the miss path IS the populate path, so prefill work is never done
        twice for a slot-matched segment. Returns None when the prefix can't
        be represented (over the buffer capacity, or a single segment over
        the largest segment bucket) — the caller falls back to cold prefill.

        ``_staged`` (``stage()``'s bookkeeping dict) collects which entry
        keys / assembled buffer this call CREATED, so a stale speculation
        can release exactly them later.
        """
        total = sum(len(ids) for _, ids in segments)
        P = self.config.max_prefix_tokens
        if total == 0 or total > P:
            return None
        max_seg = max(self.config.segment_buckets)
        if any(len(ids) > max_seg for _, ids in segments):
            return None

        chain_full = tuple(k for k, _ in segments)
        akey = (chain_full, total)
        with self._lock:
            memo = self._assembled.get(akey)
            if memo is not None:
                self._assembled.move_to_end(akey)
                self._assembled_uses[akey] = (
                    self._assembled_uses.get(akey, 0) + 1
                )
                # touch member entries so the LRU order tracks real use
                off, chain = 0, ()
                for key, ids in segments:
                    ek = self._entry_key(key, off, chain)
                    e = self._entries.get(ek)
                    if e is not None:
                        self._entries.move_to_end(ek)
                        e.uses += 1
                    off += len(ids)
                    chain = chain + (key,)
                self.hits += len(segments)
                self.tokens_reused += total
                if _staged is not None:
                    _staged["chain_key"] = akey
                    _staged["created"] = []
                    _staged["memo_new"] = False
                return CachedPrefix(
                    memo[0], memo[1], P, total, 0,
                    chain_key=akey if self.config.reuse == "exact" else None,
                )

        buf = self.engine.prefix_buffer_zero()
        off = 0
        chain: Tuple[str, ...] = ()
        reused = computed = n_hit = n_miss = 0
        created: List[tuple] = []  # (key, uses0, stamp) this resolve built
        for key, ids in segments:
            seg_len = len(ids)
            ek = self._entry_key(key, off, chain)
            with self._lock:
                e = self._entries.get(ek)
                if e is not None and e.seg_len == seg_len:
                    self._entries.move_to_end(ek)
                    e.uses += 1
                else:
                    e = None  # slot/length mismatch: treat as a miss
            if e is None:
                # build with the true left context (buf holds chain's KV):
                # under "exact" reuse this makes the block bit-faithful to
                # what a cold prefill would have computed at these slots
                planes = self.engine.build_segment_kv(list(ids), buf, off)
                e = _Entry(
                    planes=planes, seg_len=seg_len,
                    nbytes=_planes_nbytes(planes),
                    pinned=key in self._pinned_keys,
                )
                self._insert(ek, e)
                # staging identity is snapshotted HERE, at creation: uses
                # is 0 by construction and stamp was just assigned under
                # _insert's lock. Re-reading the entry at the end-of-resolve
                # lock instead would let a concurrent hit (bumping uses
                # between splices and that lock) erase the consumption
                # evidence release_staged's uses-moved check depends on
                created.append((ek, 0, e.stamp))
                n_miss += 1
                computed += seg_len
            else:
                n_hit += 1
                reused += seg_len
            buf = self.engine.splice_prefix(buf, e.planes, off)
            off += seg_len
            chain = chain + (key,)

        buf_bytes = _planes_nbytes(buf)
        with self._lock:
            self.hits += n_hit
            self.misses += n_miss
            self.tokens_reused += reused
            self.tokens_computed += computed
            # two threads can resolve the same chain concurrently (both miss
            # the memo check): drop the loser's bytes before re-assigning or
            # assembled_bytes would over-count forever
            prev = self._assembled.pop(akey, None)
            if prev is not None:
                self.assembled_bytes -= _planes_nbytes(prev[0])
            self._assembled[akey] = (buf, off)
            self._assembled_uses[akey] = 0
            self._creation_seq += 1
            self._assembled_stamp[akey] = self._creation_seq
            self.assembled_bytes += buf_bytes
            if _staged is not None:
                _staged["chain_key"] = akey
                _staged["created"] = list(created)
                _staged["memo_new"] = prev is None
                _staged["memo_stamp"] = self._assembled_stamp[akey]
            # assembled buffers are full-capacity (P-wide) planes — at 8B
            # defaults ~512 MiB EACH — so they share the ONE HBM budget with
            # the segment blocks and, being pure re-splice avoidance, evict
            # FIRST (oldest chain first; the buffer just added is kept so a
            # repeat of this very query still skips its splices)
            budget = int(self.config.hbm_budget_mb) * (1 << 20)
            cap = max(1, int(self.config.assembled_cache_entries))
            for k in list(self._assembled):
                if (
                    len(self._assembled) <= cap
                    and self.entry_bytes + self.assembled_bytes <= budget
                ):
                    break
                if k == akey:
                    continue
                self._pop_assembled(k)
        return CachedPrefix(
            buf, off, P, reused, computed,
            chain_key=akey if self.config.reuse == "exact" else None,
        )

    # -- lookahead staging (rag/lookahead.py drives these) ---------------
    def stage(self, segments: Sequence[Tuple[str, Sequence[int]]]):
        """Resolve-and-track: exactly ``prefix_for`` (the miss path IS the
        populate path), but returns ``(CachedPrefix, staging_record)`` where
        the record names every entry/assembled buffer this call CREATED —
        the handle a superseded speculation passes to ``release_staged``.
        Blocks another request consumed in the meantime are NOT released
        (their ``uses`` moved past the recorded creation value)."""
        record: Dict = {}
        cp = self.prefix_for(segments, _staged=record)
        if cp is None or not record:
            return cp, None
        return cp, record

    def release_staged(self, record: Optional[Dict]) -> int:
        """Release what a staging created and nothing else consumed since:
        ref-count-correct stale-prefetch cancellation (a shared entry — the
        pinned head, or a chunk a live request hit after staging — stays;
        so does anything REBUILT at a staged key after the staged object
        was budget-evicted, via the creation-stamp identity check).
        Returns the number of device buffers dropped."""
        if not record:
            return 0
        released = 0
        with self._lock:
            for ek, uses0, stamp0 in record.get("created", ()):
                e = self._entries.get(ek)
                if (
                    e is None or e.pinned
                    or e.stamp != stamp0  # a different entry owns this key now
                    or e.uses > uses0  # consumed since staging
                ):
                    continue
                self._entries.pop(ek)
                self.entry_bytes -= e.nbytes
                released += 1
            akey = record.get("chain_key")
            if record.get("memo_new") and akey in self._assembled:
                if (
                    self._assembled_stamp.get(akey) == record.get("memo_stamp")
                    and self._assembled_uses.get(akey, 0) <= 0
                    and self._pop_assembled(akey)
                ):
                    released += 1
        return released

    # -- LRU bookkeeping -------------------------------------------------
    def _pop_assembled(self, key) -> bool:
        """Drop one assembled buffer + its use/stamp side-table rows (the
        one place all three stay consistent; lock held by the caller)."""
        item = self._assembled.pop(key, None)
        if item is None:
            return False
        self._assembled_uses.pop(key, None)
        self._assembled_stamp.pop(key, None)
        self.assembled_bytes -= _planes_nbytes(item[0])
        return True

    def _insert(self, key, entry: _Entry) -> None:
        budget = int(self.config.hbm_budget_mb) * (1 << 20)
        with self._lock:
            self._creation_seq += 1
            entry.stamp = self._creation_seq
            old = self._entries.pop(key, None)
            if old is not None:
                self.entry_bytes -= old.nbytes
            self._entries[key] = entry
            self.entry_bytes += entry.nbytes
            # assembled buffers (pure re-splice avoidance) evict before any
            # segment block does — a block eviction costs a real re-prefill
            while (
                self._assembled
                and self.entry_bytes + self.assembled_bytes > budget
            ):
                self._pop_assembled(next(iter(self._assembled)))
            # then evict LRU-first until under budget; pinned blocks (the
            # head — reused by 100% of requests) are skipped, and the entry
            # just inserted is never its own eviction victim
            for k in list(self._entries):
                if self.entry_bytes <= budget:
                    break
                if k == key or self._entries[k].pinned:
                    continue
                victim = self._entries.pop(k)
                self.entry_bytes -= victim.nbytes
                logger.debug("prefix cache evicted %r (%d bytes)", k, victim.nbytes)

    def clear(self) -> None:
        """Drop every cached block and assembled buffer (frees the HBM)."""
        with self._lock:
            self._entries.clear()
            self._assembled.clear()
            self._assembled_uses.clear()
            self._assembled_stamp.clear()
            self.entry_bytes = 0
            self.assembled_bytes = 0
