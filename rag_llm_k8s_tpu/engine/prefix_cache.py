"""Cross-request device-resident KV prefix cache.

Every /generate request re-prefills the same fixed prompt head (BOS + system
message + "\\n\\nContext: "), and popular queries re-prefill the same
retrieved chunks — even though the engine already supports chunked prefill
with offset causality over a populated cache prefix. This module keeps those
shared segments' KV **on device** and splices them into each request's fresh
cache via ``dynamic_update_slice``, so prefill starts at the first non-shared
token (HA-RAG / SIFT: KV reuse for shared RAG prompt segments is the dominant
prefill optimization for retrieve-then-generate serving).

Anatomy:

- **Segment blocks** (``_Entry``): per-segment KV ``[L, 1, K, Sb, hd]``
  (+ fp32 scale planes under int8-KV), padded to a bucketed length ``Sb``,
  held in an HBM-budgeted LRU keyed by ``(segment_key, position_slot)``.
  RoPE makes K position-dependent, so a block is reusable only at the exact
  token offset (*slot*) it was computed at; under the default ``reuse=
  "exact"`` policy the key additionally carries the chain of segment keys
  that preceded it — K/V of layers > 0 attend over the left context, so an
  exact-chain match is what makes cached-vs-cold logits IDENTICAL (the
  parity contract tests/test_prefix_cache.py pins). ``reuse="slot"`` relaxes
  to offset-only matching (HA-RAG-style hotness reuse: an approximation
  those systems accept for the prefill savings).
- **Assembled buffers**: the fully spliced ``[L, 1, K, P, hd]`` prefix a
  request hands to ``InferenceEngine.generate_prefixed``, memoized per
  segment chain so a repeated query re-splices nothing — its whole prefix
  is one device handle and prefill touches only the per-query tail.
- **Miss path**: the first request for a segment builds its block with the
  engine's AOT segment-prefill executable (the same chunked-prefill model
  the long-prompt path uses) — prefill work equivalent to the cold path,
  plus the slice/splice — and every later slot-matched request skips it.

The cache never changes executable shapes: prefix/suffix lengths are dynamic
scalars inside a fixed ``(P, suffix_bucket, max_new)`` executable, so a new
hit pattern never triggers an AOT compile.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from rag_llm_k8s_tpu.engine.tiering import (
    HostSpillStore,
    HotnessTracker,
    dequantize_planes,
    quantize_planes,
)
from rag_llm_k8s_tpu.obs import flight
from rag_llm_k8s_tpu.resilience import faults

logger = logging.getLogger(__name__)


@dataclass
class CachedPrefix:
    """A resolved, device-resident prompt prefix ready to splice.

    ``planes`` is the KV tuple ``(k, v)`` — or ``(k, v, k_scale, v_scale)``
    under int8-KV — each ``[L, 1, K, P, hd]`` (scales ``[L, 1, K, P]``),
    with real content in slots ``[0, length)`` and don't-care beyond (the
    consumer's kv windows never reach it). Consumed by
    ``InferenceEngine.generate_prefixed`` and
    ``ContinuousEngine.admit_prefixed``.
    """

    planes: Tuple
    length: int  # real prefix tokens covered
    capacity: int  # P — the static splice-buffer width
    reused_tokens: int  # tokens whose KV came from cache hits
    computed_tokens: int  # tokens prefilled (cache misses) to build this
    # stable identity of the prefix CONTENT (the segment-key chain + total
    # length), set under exact-chain AND chunk reuse: the paged continuous
    # engine keys its block-granular sharing on it — two requests with the
    # same chain_key map the same physical pool blocks copy-free
    # (ref-counted; ContinuousEngine._admit_prefixed_paged). None under
    # "slot" reuse, whose approximate blocks are NOT content-identical.
    # (Under "chunk" the shared blocks are whatever one resolve assembled
    # for the chain — within the policy's pinned tolerance by contract.)
    chain_key: Optional[Tuple] = None
    # chunk-granular layout (reuse="chunk" only): one ChunkSpan per segment
    # in prompt order — the paged engine's per-chunk block-table assembly
    # reads these to splice registered pool blocks at arbitrary order
    # (ContinuousEngine._chunk_splice_plan). None under exact/slot reuse.
    chunks: Optional[Tuple] = None
    # approximation fingerprint (obs/shadow.py APPROXIMATIONS): which
    # lossy-by-contract mechanisms served THIS resolve — prefix_reuse
    # (any cache hit), warm_tier (an int8-round-tripped entry spliced),
    # splice / rerotate / boundary_fixup (chunk-granular shifted
    # placements). Empty when every segment was built fresh. Memo
    # re-serves carry the fingerprint recorded when the buffer was built
    # (the content IS that content).
    approx: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ChunkSpan:
    """One segment's placement inside a resolved chunk-reuse prefix: where
    it sits (``off``/``length``), which cache entry supplied it (``stamp``
    — the creation-stamp identity every install/release path checks),
    whether its content is bit-faithful to the canonical computation
    (``exact``: a canonical-position, canonical-chain hit or a fresh build
    — only these are eligible for pool-side canonical registration), and
    the boundary-correction window's token ids (``fixup_ids`` — what a
    pool-side splice re-prefills at this span's offset)."""

    key: str
    off: int
    length: int
    stamp: int
    exact: bool
    fixup_ids: Tuple[int, ...]


@dataclass
class _Entry:
    # device planes: the engine's NATIVE layout when tier is hot
    # ((k, v) — or (k, v, k_scale, v_scale) under int8-KV), the int8
    # quantized 4-tuple when warm on a bf16 engine, and None when cold
    # (the payload lives in the host spill store)
    planes: Optional[Tuple]
    seg_len: int  # real tokens (<= bucket)
    nbytes: int  # DEVICE bytes currently held (0 while cold)
    pinned: bool = False
    # consumptions since creation (every resolve that HITS this entry bumps
    # it) — lookahead staging records the creation-time value so a stale
    # speculation releases ONLY blocks nothing else touched in between
    uses: int = 0
    # creation stamp (monotonic per cache, set by _insert): staging records
    # it so a stale release never drops a DIFFERENT entry rebuilt at the
    # same key after the staged one was budget-evicted (a fresh rebuild
    # also starts at uses=0 — the use counter alone can't tell them apart).
    # Tier transitions mutate the entry IN PLACE and never touch the stamp:
    # a demote-while-prestaged keeps PR 7's creation-stamp discipline.
    stamp: int = 0
    # hotness tier (engine/tiering.py): "hot" | "warm" | "cold"
    tier: str = "hot"
    # planes went through the int8 round trip (warm demotion on a non-int8
    # engine): splices must dequantize first, and the bounded int8 drift
    # applies to everything served from this entry until it is rebuilt
    quantized: bool = False
    # chunk-granular reuse (reuse="chunk"): the CANONICAL position this
    # entry's KV was computed at — a hit at (canon_off, canon_chain) serves
    # bit-identically; any other placement re-rotates K by the position
    # delta and boundary-corrects. Unused under exact/slot reuse (their
    # keys already pin the offset).
    canon_off: int = 0
    canon_chain: Tuple = ()


def _planes_nbytes(planes: Tuple) -> int:
    return int(sum(int(p.nbytes) for p in planes))


#: warmth-manifest side table bound: segment keys whose token ids are kept
#: for cross-restart rehydration (LRU; ids, not KV — a few KB per segment)
_SEG_IDS_CAP = 256


class PrefixCache:
    """HBM-budgeted LRU of segment KV blocks + assembled prefix buffers.

    Thread-safe; device work (build/splice) runs outside the lock — entries
    and buffers are immutable device arrays, so concurrent readers never see
    a partially written block.
    """

    def __init__(self, config, engine, tiering=None):
        if config.reuse not in ("exact", "slot", "chunk"):
            raise ValueError(
                f"prefix_cache.reuse={config.reuse!r}: expected 'exact', "
                "'slot' or 'chunk'"
            )
        self.config = config
        self.engine = engine  # owning InferenceEngine (builds the blocks)
        # hotness-aware tiering (engine/tiering.py, HA-RAG): taken from the
        # explicit arg (tests) or the owning engine's config; None = every
        # entry stays hot forever — the exact pre-tiering behavior
        if tiering is None:
            tiering = getattr(
                getattr(engine, "engine_config", None), "kv_tiering", None
            )
        enabled = tiering is not None and getattr(tiering, "enabled", False)
        self.tiering = tiering if enabled else None
        if self.tiering is not None:
            self.tiering.validate()
            self.hotness = HotnessTracker(self.tiering.half_life_s)
            self.spill = HostSpillStore(self.tiering.host_spill_mb)
        else:
            self.hotness = None
            self.spill = None
        # chunk-granular reuse hotness gate: shifted splices are allowed
        # only for chunks whose decayed hit frequency clears
        # config.chunk_hot_min — the tiering tracker when tiering is on
        # (one signal for both decisions), else a cache-private tracker
        # with the same decay grammar. None outside "chunk" mode.
        if config.reuse == "chunk" and self.hotness is None:
            self._chunk_hotness = HotnessTracker(300.0)
        else:
            self._chunk_hotness = self.hotness
        # chunk-reuse outcome counters (rag_prefix_chunk_reuse_total):
        # chain_exact = served bit-identically from the canonical position,
        # spliced = reused at the canonical offset under a different chain,
        # rerotated = position-shifted via RoPE re-rotation, recompute =
        # built fresh (miss, cold chunk, or splice-fault fallback)
        self._chunk_counts: Dict[str, int] = {
            "chain_exact": 0, "spliced": 0, "rerotated": 0, "recompute": 0,
            "splice_faults": 0, "boundary_tokens": 0,
        }
        # chunk spans recorded with each assembled-memo buffer (keys ⊆
        # _assembled) so a memo hit still carries the per-chunk layout the
        # paged engine's block-table assembly consumes
        self._assembled_spans: Dict[tuple, Tuple] = {}
        # approximation fingerprints per assembled buffer (keys ⊆
        # _assembled): a memo re-serve is the SAME content the buffer was
        # built with, so the shadow auditor attributes it identically
        self._assembled_approx: Dict[tuple, Tuple[str, ...]] = {}
        # anchored at construction: the first opportunistic sweep waits a
        # full interval (a cache with nothing demotable yet should not pay
        # a sweep on its very first resolve)
        self._last_retier = time.monotonic()
        # set by the service: called (outside the lock) after a retier
        # sweep that moved anything, so pool-side registration tiers can
        # follow the cache's hotness (ContinuousEngine.set_prefix_tier via
        # run_on_engine)
        self.on_retier = None
        # tier-transition counters (read by tier_stats / rag_kv_tier_*)
        self._tier_counts: Dict[str, int] = {
            "swap_ins_lookahead": 0,
            "swap_ins_demand": 0,
            "swap_in_fallbacks": 0,
            "demotes_warm": 0,
            "demotes_cold": 0,
            "promotes": 0,
        }
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._assembled: "OrderedDict[tuple, Tuple[Tuple, int]]" = OrderedDict()
        # warmth manifest source (ISSUE 19): the token ids behind each
        # resolved segment key, LRU-bounded. KV planes cannot cross a
        # process boundary, but (key, ids) can — a warm restart re-prefills
        # the hottest segments from this table's persisted form so the
        # cache does not come back empty (_SEG_IDS_CAP bounds the memory:
        # ids are small next to the KV they describe, but not free)
        self._seg_ids: "OrderedDict[str, List[int]]" = OrderedDict()
        # consumptions per assembled buffer since creation (keys ⊆
        # _assembled) — same stale-release discipline as _Entry.uses
        self._assembled_uses: Dict[tuple, int] = {}
        # creation stamps for assembled buffers (keys ⊆ _assembled) — same
        # identity discipline as _Entry.stamp
        self._assembled_stamp: Dict[tuple, int] = {}
        self._creation_seq = 0  # feeds both stamp tables
        self._pinned_keys: set = set()
        self.entry_bytes = 0
        self.assembled_bytes = 0
        # counters (read by /metrics and bench.py)
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.tokens_computed = 0

    # -- keys -----------------------------------------------------------
    def _entry_key(self, seg_key: str, offset: int, chain: Tuple[str, ...]):
        if self.config.reuse == "slot":
            return (seg_key, offset)
        if self.config.reuse == "chunk":
            # ONE canonical entry per segment: the entry itself records the
            # position/chain it was computed at (canon_off/canon_chain) and
            # any other placement re-rotates + boundary-corrects
            return (seg_key,)
        return (seg_key, offset, chain)

    def chunk_reuse_counters(self) -> Dict[str, int]:
        """Chunk-granular reuse outcome counters (the source of
        ``rag_prefix_chunk_reuse_total``; all zero outside reuse="chunk")."""
        with self._lock:
            return dict(self._chunk_counts)

    def pin(self, seg_key: str) -> None:
        """Mark a segment key (e.g. the fixed prompt head) never-evicted."""
        with self._lock:
            self._pinned_keys.add(seg_key)
            for k, e in self._entries.items():
                if k[0] == seg_key:
                    e.pinned = True

    # -- warmth manifest (ISSUE 19) --------------------------------------
    def warmth_manifest(self, top_n: int = 8) -> List[Dict]:
        """The hottest resolved segments as JSON-ready ``{key, ids,
        tokens, score, spilled}`` records, hotness-ranked — what a
        graceful drain persists (durably, next to the WAL) so the NEXT
        incarnation can re-prefill the working set before traffic
        arrives. Only segments whose ids are still in the bounded side
        table qualify; ``spilled`` marks segments whose KV sat in the
        host spill store (HA-RAG's argument: those are exactly the
        chunks worth staging first)."""
        tracker = (
            self.hotness if self.hotness is not None
            else self._chunk_hotness
        )
        with self._lock:
            items = [(k, list(v)) for k, v in self._seg_ids.items()]
            spilled_keys = set()
            if self.spill is not None:
                for rec in self.spill.manifest():
                    ek = rec["key"]
                    spilled_keys.add(ek[0] if isinstance(ek, tuple) else ek)
        out = []
        for key, ids in items:
            score = float(tracker.score(key)) if tracker is not None else 0.0
            out.append({
                "key": key, "ids": ids, "tokens": len(ids),
                "score": round(score, 6),
                "spilled": key in spilled_keys,
            })
        out.sort(key=lambda r: (-r["score"], str(r["key"])))
        return out[:max(0, int(top_n))]

    # -- stats ----------------------------------------------------------
    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {
                "prefix_cache_hits": self.hits,
                "prefix_cache_misses": self.misses,
                "prefill_tokens_skipped": self.tokens_reused,
                "prefix_cache_entries": len(self._entries),
                # TOTAL device bytes held: segment blocks + the assembled
                # full-prefix memo buffers (both count against the budget)
                "prefix_cache_bytes": self.entry_bytes + self.assembled_bytes,
            }

    def bytes_by_device(self) -> Dict[int, int]:
        """Resident cache bytes attributed per device id (segment blocks +
        assembled buffers) — the per-device scrape view
        (``rag_prefix_cache_device_bytes``, obs/devices.py). A plane sharded
        over several devices splits its bytes evenly across them; planes
        without a ``devices()`` API (CPU test doubles) attribute to device
        0. Reads only host-side handles — no device sync."""
        out: Dict[int, int] = {}

        def _attribute(planes: Optional[Tuple]) -> None:
            if planes is None:
                return  # cold-tier entry: its bytes live in host RAM
            for p in planes:
                nbytes = int(getattr(p, "nbytes", 0))
                try:
                    devs = list(p.devices())
                except Exception:  # noqa: BLE001 — non-jax arrays: device 0
                    devs = []
                if not devs:
                    out[0] = out.get(0, 0) + nbytes
                    continue
                share = nbytes // len(devs)
                for d in devs:
                    did = int(getattr(d, "id", 0))
                    out[did] = out.get(did, 0) + share

        with self._lock:
            entries = [e.planes for e in self._entries.values()]
            buffers = [buf for buf, _ in self._assembled.values()]
        for planes in entries:
            _attribute(planes)
        for planes in buffers:
            _attribute(planes)
        return out

    # -- the one public resolve/populate entry point ---------------------
    def prefix_for(self, segments: Sequence[Tuple[str, Sequence[int]]],
                   _staged: Optional[Dict] = None,
                   _trigger: str = "demand") -> Optional[CachedPrefix]:
        """Resolve an ordered segment list ``[(key, token_ids), ...]`` into a
        spliced prefix buffer, building (and caching) any missing blocks —
        the miss path IS the populate path, so prefill work is never done
        twice for a slot-matched segment. Returns None when the prefix can't
        be represented (over the buffer capacity, or a single segment over
        the largest segment bucket) — the caller falls back to cold prefill.

        ``_staged`` (``stage()``'s bookkeeping dict) collects which entry
        keys / assembled buffer this call CREATED, so a stale speculation
        can release exactly them later. ``_trigger`` attributes any
        cold-tier swap-ins this resolve performs: ``"lookahead"`` when the
        resolve rides the lookahead prestage (the swap-in overlapped the
        previous request's decode), ``"demand"`` when it sits on a serving
        tail's critical path.
        """
        total = sum(len(ids) for _, ids in segments)
        P = self.config.max_prefix_tokens
        if total == 0 or total > P:
            return None
        max_seg = max(self.config.segment_buckets)
        if any(len(ids) > max_seg for _, ids in segments):
            return None

        chain_full = tuple(k for k, _ in segments)
        akey = (chain_full, total)
        with self._lock:
            for key, ids in segments:
                self._seg_ids[key] = list(ids)
                self._seg_ids.move_to_end(key)
            while len(self._seg_ids) > _SEG_IDS_CAP:
                self._seg_ids.popitem(last=False)
            memo = self._assembled.get(akey)
            if memo is not None:
                self._assembled.move_to_end(akey)
                self._assembled_uses[akey] = (
                    self._assembled_uses.get(akey, 0) + 1
                )
                # touch member entries so the LRU order tracks real use
                off, chain = 0, ()
                for key, ids in segments:
                    ek = self._entry_key(key, off, chain)
                    e = self._entries.get(ek)
                    if e is not None:
                        self._entries.move_to_end(ek)
                        e.uses += 1
                    # a memo hit is the hottest possible signal — the
                    # whole chain served without touching a block. The
                    # chunk-private tracker (tiering off) must see it too,
                    # or memo-dominated hot traffic would never clear the
                    # chunk_hot_min gate for its own permutations.
                    tracker = (
                        self.hotness if self.hotness is not None
                        else self._chunk_hotness
                    )
                    if tracker is not None:
                        tracker.touch(key)
                    off += len(ids)
                    chain = chain + (key,)
                self.hits += len(segments)
                self.tokens_reused += total
                if self.config.reuse == "chunk":
                    # a memo hit re-serves the assembly AS IT WAS BUILT:
                    # spans that were bit-faithful count chain_exact,
                    # drifted (rerotated/corrected) spans count spliced —
                    # the chain_exact/spliced ratio stays an honest bound
                    # on drift exposure even for memo-dominated traffic
                    memo_spans = self._assembled_spans.get(akey)
                    if memo_spans is not None:
                        for sp in memo_spans:
                            self._chunk_counts[
                                "chain_exact" if sp.exact else "spliced"
                            ] += 1
                    else:
                        self._chunk_counts["chain_exact"] += len(segments)
                if _staged is not None:
                    _staged["chain_key"] = akey
                    _staged["created"] = []
                    _staged["memo_new"] = False
                hit = CachedPrefix(
                    memo[0], memo[1], P, total, 0,
                    chain_key=(
                        akey if self.config.reuse in ("exact", "chunk")
                        else None
                    ),
                    chunks=self._assembled_spans.get(akey),
                    # the memo re-serves the content AS BUILT — same
                    # fingerprint (plus prefix_reuse: the whole chain
                    # served from cache, whatever built it originally)
                    approx=tuple(sorted(set(
                        self._assembled_approx.get(akey, ())
                    ) | {"prefix_reuse"})),
                )
            else:
                hit = None
        if hit is not None:
            flight.emit(
                "prefix_hit", segments=len(segments), tokens=total, memo=1,
            )
            # memo-dominated traffic must still converge: a service whose
            # live mix is all memo hits would otherwise never demote idle
            # entries nor fire the cache→pool tier mirror (interval-gated,
            # so this is one dict-scan every retier_interval_s at most)
            self.retier()
            return hit

        chunk_mode = self.config.reuse == "chunk"
        Wcfg = int(getattr(self.config, "boundary_tokens", 0))
        buf = self.engine.prefix_buffer_zero()
        off = 0
        chain: Tuple[str, ...] = ()
        reused = computed = n_hit = n_miss = 0
        created: List[tuple] = []  # (key, uses0, stamp) this resolve built
        spans: List[ChunkSpan] = []
        outcomes: Dict[str, int] = {}
        fixup_tokens = 0
        approx: set = set()  # this resolve's approximation fingerprint
        for key, ids in segments:
            seg_len = len(ids)
            ek = self._entry_key(key, off, chain)
            planes: Optional[Tuple] = None
            quantized = False
            swap = None  # (stamp, score) when a cold entry needs a swap-in
            outcome = None  # chunk-mode reuse outcome for this segment
            shifted = False  # takes the rotate/boundary-correct machinery
            delta = 0
            with self._lock:
                e = self._entries.get(ek)
                if e is not None and e.seg_len == seg_len:
                    self._entries.move_to_end(ek)
                    e.uses += 1
                else:
                    e = None  # slot/length mismatch: treat as a miss
                score = None
                if self.tiering is not None:
                    score = self.hotness.touch(key)
                elif chunk_mode:
                    score = self._chunk_hotness.touch(key)
                if chunk_mode and e is not None:
                    if e.canon_off == off and e.canon_chain == chain:
                        # canonical placement: bit-identical UNLESS the
                        # entry went through the warm int8 round trip —
                        # label that drift honestly (the serve path is
                        # unchanged: dequantized splice under the warm
                        # tier's tolerance contract, no rotation/fixup)
                        outcome = (
                            "chain_exact" if not e.quantized else "spliced"
                        )
                    elif score >= self.config.chunk_hot_min:
                        delta = off - e.canon_off
                        outcome = "rerotated" if delta else "spliced"
                        shifted = True
                    else:
                        # cold/one-shot chunk: the drift budget is spent
                        # only where the savings recur — rebuild at THIS
                        # position (re-canonicalizing the entry)
                        e = None
                        outcome = "recompute"
                if self.tiering is not None and e is not None:
                    if e.tier == "cold":
                        swap = (e.stamp, score)
                    elif (
                        e.tier == "warm"
                        and score >= self.tiering.warm_below
                    ):
                        # promotion roughly doubles this entry's device
                        # bytes — re-enforce the budget or a
                        # hit-dominated steady state (no inserts) could
                        # sit over it indefinitely
                        self._promote_locked(e)
                        self._enforce_budget_locked(keep=ek)
                # SNAPSHOT while still locked: tier transitions mutate the
                # entry in place, so planes/quantized must never be re-read
                # after release — a concurrent demote could hand the splice
                # a None or a half-transitioned tuple
                if e is not None and e.tier != "cold":
                    planes, quantized = e.planes, e.quantized
            if e is not None and swap is not None:
                # host→HBM swap-in OUTSIDE the lock (the transfer must not
                # serialize concurrent resolves); None = the swap failed
                # (or the host buffer is gone) and the entry was dropped —
                # fall through to recompute-from-tokens below
                res = self._swap_in(ek, swap[0], _trigger, swap[1])
                if res is None:
                    # the segment will be REBUILT from tokens below: it is
                    # a recompute, not a shifted splice — clearing these
                    # keeps the reused/computed accounting (and the
                    # chunk_splice/boundary_fixup events) honest
                    e = None
                    shifted = False
                    delta = 0
                    if outcome is not None:
                        outcome = "recompute"
                else:
                    planes, quantized = res
            e_stamp = e.stamp if e is not None else 0
            was_miss = False
            if e is not None and shifted:
                # the shifted-splice path can fault (fault site
                # chunk_splice) or fail in the rotation op: both fall back
                # to recompute-from-tokens — nothing was allocated yet, so
                # the fallback leaks zero entries/blocks by construction
                try:
                    faults.maybe_fail("chunk_splice")
                    seg_marks = {"splice"}  # fingerprint iff this succeeds
                    if quantized and len(planes) == 4:
                        planes = dequantize_planes(planes, buf[0].dtype)
                        quantized = False
                        seg_marks.add("warm_tier")
                    if delta:
                        planes = self.engine.rerotate_segment_kv(
                            planes, delta
                        )
                        flight.emit("rerotate", tokens=seg_len, delta=delta)
                        seg_marks.add("rerotate")
                    approx |= seg_marks
                except Exception:  # noqa: BLE001 — KeyboardInterrupt propagates
                    logger.warning(
                        "chunk splice failed for %r; recomputing", ek,
                        exc_info=True,
                    )
                    with self._lock:
                        self._chunk_counts["splice_faults"] += 1
                    e = None
                    outcome = "recompute"
                    shifted = False
                    planes, quantized = None, False
            if e is None:
                # build with the true left context (buf holds chain's KV):
                # under "exact" reuse this makes the block bit-faithful to
                # what a cold prefill would have computed at these slots
                planes = self.engine.build_segment_kv(list(ids), buf, off)
                e = _Entry(
                    planes=planes, seg_len=seg_len,
                    nbytes=_planes_nbytes(planes),
                    pinned=key in self._pinned_keys,
                    canon_off=off, canon_chain=chain,
                )
                self._insert(ek, e)
                # staging identity is snapshotted HERE, at creation: uses
                # is 0 by construction and stamp was just assigned under
                # _insert's lock. Re-reading the entry at the end-of-resolve
                # lock instead would let a concurrent hit (bumping uses
                # between splices and that lock) erase the consumption
                # evidence release_staged's uses-moved check depends on
                created.append((ek, 0, e.stamp))
                e_stamp = e.stamp
                was_miss = True
                n_miss += 1
                computed += seg_len
                if chunk_mode:
                    outcome = "recompute"
            else:
                n_hit += 1
            if quantized and len(planes) == 4:
                # warm entry on a non-int8 engine: rebuild native-dtype
                # planes for the splice from the LOCKED snapshot (the
                # tuple itself is immutable). The int8 round trip is the
                # warm tier's bounded drift.
                planes = dequantize_planes(planes, buf[0].dtype)
                approx.add("warm_tier")
            if not was_miss:
                approx.add("prefix_reuse")  # served (at least partly) cached
            buf = self.engine.splice_prefix(buf, planes, off)
            if shifted:
                # bounded boundary correction: re-prefill the chunk's first
                # W tokens with the TRUE left context — the slots where
                # cross-chunk attention actually differs from the canonical
                # computation. The corrected block overwrites exactly its
                # window (the re-rotated tail stays).
                W = min(Wcfg, seg_len)
                if W > 0:
                    fix = self.engine.build_segment_kv(ids[:W], buf, off)
                    buf = self.engine.splice_prefix(
                        buf, self.engine.slice_prefix_block(fix, W), off
                    )
                    flight.emit("boundary_fixup", tokens=W)
                    approx.add("boundary_fixup")
                    fixup_tokens += W
                    computed += W
                    reused += seg_len - W
                else:
                    reused += seg_len
                flight.emit(
                    "chunk_splice", tokens=seg_len, delta=delta,
                )
            elif not was_miss:
                # exact/slot hit, or a chunk-mode canonical-position hit
                reused += seg_len
            if outcome is not None:
                outcomes[outcome] = outcomes.get(outcome, 0) + 1
            if chunk_mode:
                spans.append(ChunkSpan(
                    key=key, off=off, length=seg_len, stamp=e_stamp,
                    exact=outcome in ("chain_exact", "recompute"),
                    fixup_ids=tuple(int(t) for t in ids[:Wcfg]),
                ))
            off += seg_len
            chain = chain + (key,)

        buf_bytes = _planes_nbytes(buf)
        with self._lock:
            self.hits += n_hit
            self.misses += n_miss
            self.tokens_reused += reused
            self.tokens_computed += computed
            for k, v in outcomes.items():
                self._chunk_counts[k] += v
            self._chunk_counts["boundary_tokens"] += fixup_tokens
            # two threads can resolve the same chain concurrently (both miss
            # the memo check): drop the loser's bytes before re-assigning or
            # assembled_bytes would over-count forever
            prev = self._assembled.pop(akey, None)
            if prev is not None:
                self.assembled_bytes -= _planes_nbytes(prev[0])
            self._assembled[akey] = (buf, off)
            # a memo re-serve is THIS content: record the fingerprint so
            # the shadow auditor attributes repeats identically
            self._assembled_approx[akey] = tuple(sorted(approx))
            if chunk_mode:
                self._assembled_spans[akey] = tuple(spans)
            self._assembled_uses[akey] = 0
            self._creation_seq += 1
            self._assembled_stamp[akey] = self._creation_seq
            self.assembled_bytes += buf_bytes
            if _staged is not None:
                _staged["chain_key"] = akey
                _staged["created"] = list(created)
                _staged["memo_new"] = prev is None
                _staged["memo_stamp"] = self._assembled_stamp[akey]
            # assembled buffers are full-capacity (P-wide) planes — at 8B
            # defaults ~512 MiB EACH — so they share the ONE HBM budget with
            # the segment blocks and, being pure re-splice avoidance, evict
            # FIRST (coldest chain first, then oldest; the buffer just
            # added is kept so a repeat of this very query still skips its
            # splices)
            budget = int(self.config.hbm_budget_mb) * (1 << 20)
            cap = max(1, int(self.config.assembled_cache_entries))
            if (
                len(self._assembled) > cap
                or self.entry_bytes + self.assembled_bytes > budget
            ):
                # order computed only under pressure: ranking every memo's
                # chain tier scores every member segment, too much for the
                # common nothing-to-evict resolve
                for k in self._assembled_evict_order():
                    if (
                        len(self._assembled) <= cap
                        and self.entry_bytes + self.assembled_bytes <= budget
                    ):
                        break
                    if k == akey:
                        continue
                    self._pop_assembled(k)
        if n_hit:
            flight.emit("prefix_hit", segments=n_hit, tokens=reused)
        if n_miss:
            flight.emit("prefix_miss", segments=n_miss, tokens=computed)
        # opportunistic tier maintenance (interval-gated; no-op untiered):
        # demotions ride the resolve path so a quiet cache still converges
        # without a dedicated thread — the lookahead sweeper's stage()
        # calls and live resolves both pass through here
        self.retier()
        return CachedPrefix(
            buf, off, P, reused, computed,
            chain_key=(
                akey if self.config.reuse in ("exact", "chunk") else None
            ),
            chunks=tuple(spans) if chunk_mode else None,
            approx=tuple(sorted(approx)),
        )

    # -- lookahead staging (rag/lookahead.py drives these) ---------------
    def stage(self, segments: Sequence[Tuple[str, Sequence[int]]],
              trigger: str = "lookahead"):
        """Resolve-and-track: exactly ``prefix_for`` (the miss path IS the
        populate path), but returns ``(CachedPrefix, staging_record)`` where
        the record names every entry/assembled buffer this call CREATED —
        the handle a superseded speculation passes to ``release_staged``.
        Blocks another request consumed in the meantime are NOT released
        (their ``uses`` moved past the recorded creation value).

        ``trigger`` attributes the resolve's cold-tier swap-ins: staging is
        the lookahead pipeline's prestage, so a swap-in here happened OFF
        the critical path — overlapped with the previous request's decode —
        and counts toward the swap-in hide rate."""
        record: Dict = {}
        cp = self.prefix_for(segments, _staged=record, _trigger=trigger)
        if cp is None or not record:
            return cp, None
        return cp, record

    def release_staged(self, record: Optional[Dict]) -> int:
        """Release what a staging created and nothing else consumed since:
        ref-count-correct stale-prefetch cancellation (a shared entry — the
        pinned head, or a chunk a live request hit after staging — stays;
        so does anything REBUILT at a staged key after the staged object
        was budget-evicted, via the creation-stamp identity check).
        Returns the number of device buffers dropped."""
        if not record:
            return 0
        released = 0
        with self._lock:
            for ek, uses0, stamp0 in record.get("created", ()):
                e = self._entries.get(ek)
                if (
                    e is None or e.pinned
                    or e.stamp != stamp0  # a different entry owns this key now
                    or e.uses > uses0  # consumed since staging
                ):
                    continue
                self._entries.pop(ek)
                self.entry_bytes -= e.nbytes
                if self.spill is not None:
                    # demote-while-prestaged: a staged entry that went cold
                    # before the speculation died still releases its HOST
                    # buffer (its device bytes were already spilled away)
                    self.spill.drop(ek)
                released += 1
            akey = record.get("chain_key")
            if record.get("memo_new") and akey in self._assembled:
                if (
                    self._assembled_stamp.get(akey) == record.get("memo_stamp")
                    and self._assembled_uses.get(akey, 0) <= 0
                    and self._pop_assembled(akey)
                ):
                    released += 1
        return released

    # -- hotness tiering (engine/tiering.py drives the representation) ----
    def retier(self, force: bool = False) -> int:
        """One tier-maintenance sweep: demote entries whose decayed hotness
        fell under the thresholds (hot → warm int8 in place, any → cold
        host spill). Interval-gated on the resolve path (``force=True``
        ignores the gate — tests and service maintenance). Pinned entries
        (the prompt head — reused by 100% of requests) never demote.
        Returns the number of transitions performed.

        Invariants preserved across every transition: the ``_Entry`` object
        (and its creation stamp / use counter) is mutated in place, so the
        PR-7 staging discipline and LRU identity survive; ``entry_bytes``
        tracks device bytes exactly (a cold entry holds zero)."""
        if self.tiering is None:
            return 0
        now = time.monotonic()
        cold: List[tuple] = []  # (ek, planes snapshot) to spill off-lock
        with self._lock:
            if (
                not force
                and now - self._last_retier < self.tiering.retier_interval_s
            ):
                return 0
            self._last_retier = now
            moved = 0
            for ek, e in list(self._entries.items()):
                if e.pinned:
                    continue
                if e.tier == "cold" and ek not in self.spill:
                    # the host store's budget evicted its backing: this
                    # entry can never swap in again (its next use is a
                    # plain miss either way) — drop the stub, or cold
                    # entries accrete one dict node per chunk ever cached
                    self._entries.pop(ek)
                    continue
                score = self.hotness.score(ek[0])
                if e.tier != "cold" and score < self.tiering.cold_below:
                    cold.append((ek, e.planes))
                elif e.tier == "hot" and score < self.tiering.warm_below:
                    # quantization only DISPATCHES device work (async) —
                    # cheap to hold the lock across, unlike a D2H copy
                    self._demote_warm_locked(e)
                    moved += 1
            self.hotness.prune()
        for ek, planes in cold:
            # the device→host copy of a multi-MiB chunk must not serialize
            # concurrent resolves (the rule _swap_in applies in the other
            # direction): copy OUTSIDE the lock, install under a short
            # re-acquire gated on plane IDENTITY — an entry rebuilt,
            # promoted, or already spilled meanwhile is skipped and the
            # next sweep re-judges it
            host = tuple(np.asarray(p) for p in planes)
            with self._lock:
                e = self._entries.get(ek)
                if e is None or e.planes is not planes:
                    continue
                self._spill_host_locked(ek, e, host)
                moved += 1
        if moved:
            flight.emit("retier", moved=moved)
        if moved and self.on_retier is not None:
            try:
                self.on_retier()
            except Exception:  # noqa: BLE001 — maintenance must not fail a resolve
                logger.exception("prefix-cache retier callback failed")
        return moved

    def force_demote(self, tier: str, seg_key: Optional[str] = None) -> int:
        """Demote entries (all, or just ``seg_key``'s) to ``tier``
        regardless of hotness — the bench's forced-demotion lever and the
        quality-tolerance tests' setup hook. Pinned entries still never
        demote. Returns the number of entries moved."""
        if tier not in ("warm", "cold"):
            raise ValueError(f"force_demote tier={tier!r}: expected warm|cold")
        if self.tiering is None:
            return 0
        n = 0
        with self._lock:
            for ek, e in list(self._entries.items()):
                if e.pinned or (seg_key is not None and ek[0] != seg_key):
                    continue
                if tier == "cold" and e.tier != "cold":
                    self._demote_cold_locked(ek, e)
                    n += 1
                elif tier == "warm" and e.tier == "hot":
                    self._demote_warm_locked(e)
                    n += 1
        return n

    def _demote_warm_locked(self, e: _Entry) -> None:
        """hot → warm: quantize the entry's planes to int8 IN PLACE (no
        re-prefill — the bytes already in HBM convert; the old planes free
        when their last reference drops). On an int8-KV engine the planes
        are already int8, so warm is a tier label with no byte change."""
        self._tier_counts["demotes_warm"] += 1
        q = quantize_planes(e.planes)
        e.tier = "warm"
        if q is None:
            return  # already int8 — label-only transition
        self.entry_bytes -= e.nbytes
        e.planes = q
        e.quantized = True
        e.nbytes = _planes_nbytes(q)
        self.entry_bytes += e.nbytes

    def _demote_cold_locked(self, ek, e: _Entry) -> None:
        """(hot|warm) → cold: copy the planes to host RAM and drop the
        device bytes. A hot entry spilled cold and swapped back is still
        BYTE-EXACT — only the warm int8 round trip costs drift. The D2H
        copy here runs UNDER the lock — acceptable for ``force_demote``
        (a test/ops lever); the retier sweep copies outside it."""
        self._spill_host_locked(
            ek, e, tuple(np.asarray(p) for p in e.planes)
        )

    def _spill_host_locked(self, ek, e: _Entry, host: Tuple) -> None:
        """Install an already-host-copied spill and zero the entry's
        device residency (lock held by the caller)."""
        self.spill.put(ek, host, meta={"quantized": e.quantized})
        self.entry_bytes -= e.nbytes
        e.planes = None
        e.nbytes = 0
        e.tier = "cold"
        self._tier_counts["demotes_cold"] += 1

    def _promote_locked(self, e: _Entry) -> None:
        """warm → hot for an entry whose hotness recovered: materialize the
        native-dtype planes so hits stop paying the per-resolve dequant.
        The int8 drift is retained (the original bits are gone — exactness
        returns only when the entry is rebuilt); an int8-KV engine's warm
        entries promote by label alone."""
        self._tier_counts["promotes"] += 1
        if not e.quantized:
            e.tier = "hot"
            return
        native = dequantize_planes(e.planes, self._native_dtype())
        self.entry_bytes -= e.nbytes
        e.planes = native
        e.quantized = False
        e.nbytes = _planes_nbytes(native)
        e.tier = "hot"
        self.entry_bytes += e.nbytes

    def _swap_in(self, ek, stamp: int, trigger: str, score: float):
        """cold → resident, performed OUTSIDE the cache lock: the host→HBM
        transfer of a multi-MiB chunk must not serialize every concurrent
        resolve (memo hits included). The spill store guards itself, the
        device_put runs unlocked, and the result installs under a short
        re-acquire gated on the entry's creation STAMP — a concurrent
        rebuild or a second swap-in wins and this call's staged planes are
        simply dropped. Returns ``(planes, quantized)`` ready to splice, or
        None when the swap could not happen — the entry and its host buffer
        are dropped and the caller RECOMPUTES FROM TOKENS (the chaos
        contract: a failed swap-in is a cache miss, never an error).
        ``kv_swap_in`` is the fault site."""

        def _drop_if_ours():
            e = self._entries.get(ek)
            if e is not None and e.stamp == stamp and e.tier == "cold":
                self._entries.pop(ek)

        item = self.spill.get(ek)
        if item is None:
            # the host store evicted it (budget): an ordinary miss
            with self._lock:
                _drop_if_ours()
            return None
        try:
            faults.maybe_fail("kv_swap_in")
            planes = self._device_planes(item[0])
        except Exception:  # recompute-from-tokens fallback; KeyboardInterrupt
            # / SystemExit must PROPAGATE (nothing here is torn: the entry
            # is still cold and the spill intact — a later resolve retries)
            with self._lock:
                self._tier_counts["swap_in_fallbacks"] += 1
                e = self._entries.get(ek)
                if e is None or (e.stamp == stamp and e.tier == "cold"):
                    # ours (or an orphan): the host buffer releases with
                    # the entry. A DIFFERENT entry rebuilt at this key
                    # meanwhile may own a NEW spill — leave it alone, or a
                    # failed swap would silently turn that cached chunk
                    # into a recompute (same stamp aliasing every other
                    # release path guards against)
                    if e is not None:
                        self._entries.pop(ek)
                    self.spill.drop(ek)
            logger.warning(
                "kv swap-in failed for %r; falling back to recompute",
                ek, exc_info=True,
            )
            flight.emit("swap_in_fallback")
            return None
        with self._lock:
            e = self._entries.get(ek)
            if e is None or e.stamp != stamp:
                return None  # rebuilt/evicted meanwhile: plain miss
            if e.tier != "cold":
                # a concurrent swap-in won: serve ITS installed planes
                return (e.planes, e.quantized)
            self.spill.drop(ek)
            e.planes = planes
            e.nbytes = _planes_nbytes(planes)
            e.tier = "warm" if e.quantized else "hot"
            self.entry_bytes += e.nbytes
            key = (
                "swap_ins_lookahead" if trigger == "lookahead"
                else "swap_ins_demand"
            )
            self._tier_counts[key] += 1
            flight.emit("swap_in", trigger=trigger)
            if e.tier == "warm" and score >= self.tiering.warm_below:
                # the hit that triggered this swap already re-heated the
                # chunk: promote in the same install (rehit contract)
                self._promote_locked(e)
            self._enforce_budget_locked(keep=ek)
            return (e.planes, e.quantized)

    def _device_planes(self, host: Tuple) -> Tuple:
        """Host numpy planes back onto the device (replicated on a mesh —
        the layout every entry built by ``build_segment_kv`` has)."""
        import jax
        import jax.numpy as jnp

        planes = tuple(jnp.asarray(p) for p in host)
        mesh = getattr(self.engine, "mesh", None)
        if mesh is not None:
            planes = tuple(
                jax.device_put(p, mesh.replicated) for p in planes
            )
        return planes

    def _native_dtype(self):
        """The engine's native KV payload dtype (what splices consume)."""
        return self.engine.prefix_buffer_zero()[0].dtype

    def chain_tier(self, chain_key) -> str:
        """The hotness tier of a whole CHAIN (a pool registration's unit —
        ``(segment-key tuple, total)``): as cold as its coldest member
        segment. Pure hotness math, no entry lookups — usable from any
        thread for pool-side retier decisions."""
        if self.tiering is None or chain_key is None:
            return "hot"
        chain = chain_key[0] if isinstance(chain_key, tuple) else chain_key
        worst = "hot"
        for seg in chain:
            s = self.hotness.score(seg)
            if s < self.tiering.cold_below:
                return "cold"
            if s < self.tiering.warm_below:
                worst = "warm"
        return worst

    def tier_stats(self) -> Dict[str, float]:
        """Per-tier residency + transition counters — the source of the
        ``rag_kv_tier_*`` families (obs) and the bench's capacity math."""
        out: Dict[str, float] = {
            "tier_hot_entries": 0, "tier_warm_entries": 0,
            "tier_cold_entries": 0, "tier_hot_bytes": 0,
            "tier_warm_bytes": 0, "tier_cold_host_bytes": 0,
            "tier_host_evictions": 0,
        }
        with self._lock:
            for e in self._entries.values():
                out[f"tier_{e.tier}_entries"] += 1
                if e.tier != "cold":
                    out[f"tier_{e.tier}_bytes"] += e.nbytes
            out.update(self._tier_counts)
        if self.spill is not None:
            out["tier_cold_host_bytes"] = self.spill.bytes
            out["tier_host_evictions"] = self.spill.evictions
        return out

    # -- LRU bookkeeping -------------------------------------------------
    def _assembled_evict_order(self) -> List[tuple]:
        """Assembled-memo eviction order (lock held by the caller):
        COLDEST chain first — a memo whose coldest member segment demoted
        is re-splice avoidance for a chain the tier policy already judged
        idle, so its full-capacity buffer is the cheapest HBM to give back
        (the open item carried since the tiering PR) — then LRU within a
        tier. Untiered caches keep pure LRU (every chain reads "hot")."""
        keys = list(self._assembled)  # OrderedDict: LRU-oldest first
        if self.tiering is None:
            return keys
        rank = {"cold": 0, "warm": 1, "hot": 2}
        order = {k: i for i, k in enumerate(keys)}
        return sorted(
            keys,
            key=lambda k: (rank.get(self.chain_tier(k), 2), order[k]),
        )

    def _pop_assembled(self, key) -> bool:
        """Drop one assembled buffer + its use/stamp side-table rows (the
        one place all three stay consistent; lock held by the caller)."""
        item = self._assembled.pop(key, None)
        if item is None:
            return False
        self._assembled_uses.pop(key, None)
        self._assembled_stamp.pop(key, None)
        self._assembled_spans.pop(key, None)
        self._assembled_approx.pop(key, None)
        self.assembled_bytes -= _planes_nbytes(item[0])
        return True

    def _insert(self, key, entry: _Entry) -> None:
        with self._lock:
            self._creation_seq += 1
            entry.stamp = self._creation_seq
            old = self._entries.pop(key, None)
            if old is not None:
                self.entry_bytes -= old.nbytes
                if self.spill is not None:
                    self.spill.drop(key)  # a cold old entry's host buffer
            self._entries[key] = entry
            self.entry_bytes += entry.nbytes
            self._enforce_budget_locked(keep=key)

    def _enforce_budget_locked(self, keep) -> None:
        """Evict down to the HBM budget (lock held). Assembled buffers
        (pure re-splice avoidance) evict before any segment block does — a
        block eviction costs a real re-prefill — coldest chain first under
        tiering (``_assembled_evict_order``); then blocks evict LRU-first. Pinned blocks (the head — reused by 100% of requests)
        and ``keep`` (the entry just inserted / swapped in) are never
        victims, and cold entries are skipped — they hold no device bytes
        to reclaim."""
        budget = int(self.config.hbm_budget_mb) * (1 << 20)
        if self._assembled and self.entry_bytes + self.assembled_bytes > budget:
            for k in self._assembled_evict_order():
                if self.entry_bytes + self.assembled_bytes <= budget:
                    break
                self._pop_assembled(k)
        for k in list(self._entries):
            if self.entry_bytes <= budget:
                break
            e = self._entries[k]
            if k == keep or e.pinned or e.tier == "cold":
                continue
            self._entries.pop(k)
            self.entry_bytes -= e.nbytes
            logger.debug("prefix cache evicted %r (%d bytes)", k, e.nbytes)

    def clear(self) -> None:
        """Drop every cached block and assembled buffer (frees the HBM) —
        and every cold-spilled host buffer with them: a cleared cache must
        leave ZERO host-spill bookkeeping behind (the reset contract the
        tiering chaos tests pin)."""
        with self._lock:
            self._entries.clear()
            self._assembled.clear()
            self._assembled_uses.clear()
            self._assembled_stamp.clear()
            self._assembled_spans.clear()
            self._assembled_approx.clear()
            self.entry_bytes = 0
            self.assembled_bytes = 0
            if self.spill is not None:
                self.spill.clear()
