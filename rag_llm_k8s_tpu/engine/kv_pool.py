"""Block-pool KV allocation for the continuous engine (paged KV cache).

The dense slot layout allocates one ``(L, B, K, T, hd)`` cache with
``T = max_seq_len`` for EVERY slot, so a 64-slot batch pays full-window HBM
and decode bandwidth for rows holding a 300-token prompt — BENCH_r05 shows
device decode steps/s collapsing 250 → 90 from B=8 to B=64 on exactly that
waste. PagedAttention (vLLM; Kwon et al. 2023) and JetStream's TPU serving
design both make the same move: carve the KV arena into fixed-size physical
**blocks**, give every row an int32 *block table* mapping its logical token
positions onto pool blocks, and allocate blocks only as a row's frontier
actually reaches them.

This module is the HOST-side allocator — pure bookkeeping, no jax imports:

- **free list**: physical block ids are handed out O(1) from a deque and
  returned on release; no compaction is ever needed (any block serves any
  logical position — the table provides the indirection);
- **ref counts**: a block mapped into several rows' tables (prefix-cache
  hits sharing a prompt head) is freed only when its LAST reader releases
  it, which is what makes shared prefix blocks copy-free;
- **the null block**: physical block 0 is RESERVED and never allocated.
  Table entries for logical blocks a row has not reached (or fully-padded
  regions) point at it; device code may harmlessly write junk there and the
  attention kernels never read it (out-of-window blocks are skipped), so
  executables can keep static loop shapes without per-block conditionals;
- **exhaustion is an exception, not a crash**: ``alloc`` is all-or-nothing
  and raises :class:`PoolExhausted`; the engine turns that into admission
  backpressure (requests wait in the queue → the PR-4 admission gate sheds
  429s) or mid-decode preemption, never an OOM abort.

The device arena itself — ``(L, num_blocks, K, block_size, hd)`` plus scale
planes under int8-KV — is engine state (it is donated through the step
executables); the pool only tracks which physical ids are live.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Iterable, List

from rag_llm_k8s_tpu.obs import flight

__all__ = ["KVBlockPool", "PoolExhausted", "NULL_BLOCK"]

# physical block 0: reserved write-sink / never-read placeholder (see module
# docstring). Every block table starts life filled with it.
NULL_BLOCK = 0


class PoolExhausted(RuntimeError):
    """The pool cannot serve an allocation right now.

    Deliberately NOT an OOM: every block is accounted for, the device arena
    is intact, and freeing any row (retire / eviction / preemption) makes
    the allocation servable again. Callers translate this into
    backpressure, not a reset.
    """

    def __init__(self, requested: int, available: int):
        super().__init__(
            f"kv pool exhausted: requested {requested} block(s), "
            f"{available} free"
        )
        self.requested = requested
        self.available = available


class KVBlockPool:
    """Free-list + ref-count allocator over ``num_blocks`` physical blocks
    of ``block_size`` tokens each (block 0 reserved as the null block).

    Thread-safe: the scheduler thread owns the hot path, but prefix-block
    pinning and metric scrapes arrive from other threads; every method
    takes the one small lock.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"kv pool needs >= 2 blocks (1 reserved null + 1 usable), "
                f"got {num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size={block_size}: expected >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._lock = threading.Lock()
        # LIFO reuse: a just-freed block's arena region is the likeliest to
        # still be resident in any cache hierarchy, and tests get
        # deterministic id sequences either way
        self._free: deque = deque(range(1, self.num_blocks))
        self._refs: Dict[int, int] = {}
        # cumulative counters (engine stats / bench)
        self.total_allocs = 0
        self.total_exhaustions = 0
        # per-tier occupancy of REGISTERED prefix blocks (hotness tiering,
        # engine/tiering.py): the engine accounts each registration's
        # blocks under its chunk's tier at register/drop/retier time, so
        # admission can tell "the pool is full of hot rows" (true pressure)
        # from "the pool is full of demotable cache warmth" (reclaimable).
        # Pure bookkeeping — the allocator itself is tier-oblivious.
        self._tier_blocks: Dict[str, int] = {"hot": 0, "warm": 0, "cold": 0}

    # -- capacity -------------------------------------------------------
    def blocks_for(self, tokens: int) -> int:
        """Blocks needed to cover ``tokens`` logical positions. Also the
        incremental-admission arithmetic: a chunked prefill (ISSUE 16)
        grows a row per scheduled chunk by
        ``blocks_for(progress + chunk) - blocks_for(progress)`` instead of
        paying the whole prompt's allocation up front."""
        return max(0, -(-int(tokens) // self.block_size))

    def available(self) -> int:
        with self._lock:
            return len(self._free)

    def blocks_in_use(self) -> int:
        with self._lock:
            return (self.num_blocks - 1) - len(self._free)

    def usable_blocks(self) -> int:
        """Allocatable capacity (total minus the reserved null block)."""
        return self.num_blocks - 1

    def can_alloc(self, n: int) -> bool:
        with self._lock:
            return n <= len(self._free)

    def fragmentation(self, used_tokens: int) -> float:
        """INTERNAL fragmentation: the fraction of allocated token slots not
        holding live KV (``1 - used / (in_use * block_size)``). External
        fragmentation cannot exist here — any free block satisfies any
        request — so this is the number worth a gauge: it is the pad/waste
        the paged layout still pays (bounded by one block per row plus
        ref-shared prefix tails) vs the dense layout's full-window waste."""
        in_use = self.blocks_in_use()
        if in_use <= 0:
            return 0.0
        cap = in_use * self.block_size
        return max(0.0, min(1.0, 1.0 - float(used_tokens) / cap))

    # -- alloc / ref / free --------------------------------------------
    def alloc(self, n: int) -> List[int]:
        """Take ``n`` blocks (refcount 1 each) — ALL-OR-NOTHING. Raises
        :class:`PoolExhausted` without side effects when short."""
        if n <= 0:
            return []
        with self._lock:
            free = len(self._free)
            if n > free:
                self.total_exhaustions += 1
                ids = None
            else:
                ids = [self._free.pop() for _ in range(n)]
                for b in ids:
                    self._refs[b] = 1
                self.total_allocs += n
                free -= n
        # journal outside the lock: the flight recorder is lock-cheap but
        # the allocator's lock is on the admission hot path
        if ids is None:
            flight.emit("pool_exhausted", requested=n, free=free)
            raise PoolExhausted(n, free)
        flight.emit("pool_alloc", blocks=n, free=free)
        return ids

    def ref(self, ids: Iterable[int]) -> None:
        """Add one reference to each block (prefix sharing: a row mapping a
        cached block into its table pins it for the row's lifetime)."""
        with self._lock:
            for b in ids:
                if b == NULL_BLOCK:
                    continue
                if b not in self._refs:
                    raise ValueError(f"ref() of unallocated block {b}")
                self._refs[b] += 1

    def free(self, ids: Iterable[int]) -> int:
        """Drop one reference per block; blocks reaching zero return to the
        free list. Null blocks and duplicates-after-zero are rejected loudly
        (a double free is a table-bookkeeping bug, not a runtime condition).
        Returns how many blocks actually became free."""
        reclaimed = 0
        with self._lock:
            for b in ids:
                if b == NULL_BLOCK:
                    continue
                refs = self._refs.get(b)
                if refs is None:
                    raise ValueError(f"free() of unallocated block {b}")
                if refs <= 1:
                    del self._refs[b]
                    self._free.append(b)
                    reclaimed += 1
                else:
                    self._refs[b] = refs - 1
            free = len(self._free)
        if reclaimed:
            flight.emit("pool_free", blocks=reclaimed, free=free)
        return reclaimed

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._refs.get(block, 0)

    # -- tier accounting (hotness-aware KV tiering) ---------------------
    def account_tier(self, tier: str, delta: int) -> None:
        """Move ``delta`` registered-prefix blocks into ``tier``'s ledger
        (negative = out). The engine calls this at registration, drop, and
        retier sites; clamped at zero so a double-drop can't go negative."""
        if tier not in self._tier_blocks:
            raise ValueError(
                f"unknown kv tier {tier!r}; tiers: {tuple(self._tier_blocks)}"
            )
        with self._lock:
            self._tier_blocks[tier] = max(0, self._tier_blocks[tier] + delta)

    def tier_occupancy(self) -> Dict[str, int]:
        """Registered-prefix blocks per tier + the non-registration rest
        (``rows`` — blocks owned by live decode rows, derived)."""
        with self._lock:
            out = dict(self._tier_blocks)
            registered = sum(out.values())
            in_use = (self.num_blocks - 1) - len(self._free)
            out["rows"] = max(0, in_use - registered)
            return out

    def reset(self) -> None:
        """Return EVERY block to the free list (engine reset: the arena is
        rebuilt and every table with it — holding stale refs would leak the
        pool a reset at a time; tests assert zero leaked blocks after the
        chaos lane's EngineStateLost)."""
        with self._lock:
            self._refs.clear()
            self._free = deque(range(1, self.num_blocks))
            # registrations died with the arena: their tier ledgers must
            # read zero or admission would see phantom reclaimable warmth
            for t in self._tier_blocks:
                self._tier_blocks[t] = 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            in_use = (self.num_blocks - 1) - len(self._free)
            return {
                "kv_pool_blocks_total": self.num_blocks - 1,
                "kv_pool_blocks_in_use": in_use,
                "kv_pool_blocks_free": len(self._free),
                "kv_pool_allocs_total": self.total_allocs,
                "kv_pool_exhaustions_total": self.total_exhaustions,
                "kv_pool_tier_hot_blocks": self._tier_blocks["hot"],
                "kv_pool_tier_warm_blocks": self._tier_blocks["warm"],
            }

    def __repr__(self) -> str:  # debugging / log lines
        s = self.stats()
        return (
            f"KVBlockPool(bs={self.block_size}, "
            f"in_use={s['kv_pool_blocks_in_use']}/{s['kv_pool_blocks_total']})"
        )
