"""Continuous (slot-based) batching: requests join the running decode batch.

The reference serves strictly sequentially — one ``model.generate`` at a
time on a single-threaded Flask dev server (/root/reference/llm/rag.py:204);
a request arriving mid-generation waits for the whole previous one. The
coalescing ``BatchScheduler`` (engine/batching.py) improved that to
group-at-start, but nothing could join a batch in flight.

Here decoding runs over ``B`` persistent KV **slots** with per-row cache
frontiers (``LlamaModel(row_frontier=True)``: each row's fed token is
scatter-written at its own ``kv_len``), so rows at different generation
depths decode together. Between device steps the scheduler admits waiting
requests into free slots — a request arriving mid-generation starts decoding
on the very next step instead of queueing behind the current batch.

Anatomy (all AOT-compiled, static shapes):
- ``_prefill(S)``: one B=1 forward over a bucketed prompt → that row's
  ``[L, 1, K, S, hd]`` KV block + the first sampled token;
- ``_insert(S)``: splice the KV block + per-row state into slot ``row``;
- ``_step``: ``decode_sync_steps`` decode tokens for all ``B`` slots (per-row
  windows mask inactive/mismatched rows) as one device program, returning a
  ``[k, B]`` token plane to the host — one transfer per window, overlapped
  with the next admission check. ``k = 1`` admits between every token;
  ``k > 1`` amortizes dispatch/fetch latency (decisive on a slow host link)
  for up to ``k`` steps of admission latency.

Trade-off vs the fused one-shot path (engine.py): per-window host sync and a
scatter cache write, in exchange for no head-of-line blocking. The one-shot
path remains the fastest way to run a KNOWN batch (bench.py uses it).
"""

from __future__ import annotations

import itertools
import logging
import queue
import random
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.core.mesh import MeshContext
from rag_llm_k8s_tpu.engine.engine import (
    EngineStats,
    _isin,
    maybe_fuse_params,
    maybe_quantize_params,
    param_avals,
)
from rag_llm_k8s_tpu.engine.kv_pool import KVBlockPool, NULL_BLOCK, PoolExhausted
from rag_llm_k8s_tpu.engine.sampling import (
    accept_drafts,
    sample_targets_per_row,
    sample_token_per_row,
)
from rag_llm_k8s_tpu.engine.speculative import (
    adaptive_draft_len,
    fold_acceptance,
    prompt_lookup_draft,
)
from rag_llm_k8s_tpu.models.llama import (
    LlamaModel,
    make_kv_arena,
    make_kv_cache,
    mask_window,
)
from rag_llm_k8s_tpu.obs import flight
from rag_llm_k8s_tpu.obs import goodput as obs_goodput
from rag_llm_k8s_tpu.obs import metrics as obs_metrics
from rag_llm_k8s_tpu.resilience import faults
from rag_llm_k8s_tpu.resilience.deadline import Deadline, DeadlineExceeded
# the scheduler's decision core lives behind the sim seam (ISSUE 17):
# admission verdicts, window planning, budget splits and preemption
# ordering are pure functions in sim/policy.py, shared verbatim with the
# replay driver and the pure-host simulator — this module keeps only the
# device execution and the stateful reclaim loops around them
from rag_llm_k8s_tpu.sim import policy as sim_policy
from rag_llm_k8s_tpu.utils.buckets import bucket_len

logger = logging.getLogger(__name__)

# request ids are PROCESS-global (not per-scheduler): the flight journal
# (obs/flight.py) keys every lifecycle event on this id, and two schedulers
# in one process (bench legs, tests) must never alias each other's
# timelines. itertools.count is atomic under CPython — no lock needed.
_REQUEST_IDS = itertools.count(1)


def _tenant_attr(ledger, rid: int) -> Dict[str, str]:
    """``{"tenant": ...}`` for an admit-time flight emit when the edge
    stamped one on this request (goodput.note_tenant at submit), else
    empty — admit sites only know the rid, and an un-attributed journal
    must not grow ``tenant: None`` noise on every event."""
    t = ledger.tenant_of(rid) if ledger is not None else None
    return {"tenant": t} if t else {}


class EngineStateLost(RuntimeError):
    """A device failure invalidated donated engine buffers; the engine has
    been reset and every request that was in flight is gone."""


@dataclass
class _Slot:
    """Host-side view of one device slot."""

    request_id: int = -1
    tokens: List[int] = field(default_factory=list)
    remaining: int = 0
    active: bool = False
    # paged mode only: the host mirror of this row's logical frontier (an
    # UPPER bound — EOS mid-window stops the device early; the mirror only
    # drives block pre-allocation, over-allocation frees at retire), the
    # admission sequence (preemption picks the newest victims first), and
    # the prompt's true token count (resubmission bookkeeping)
    kv_ub: int = 0
    admit_seq: int = 0
    prompt_len: int = 0
    shared_tokens: int = 0  # tokens served by ref-shared prefix blocks
    # speculative decoding (spec_paged): the row's draft corpus — the
    # assembled prompt + every emitted token, the history prompt-lookup
    # matches over — and the decayed acceptance EMA that drives its
    # adaptive draft length (None = no evidence yet; engine/speculative.py)
    history: List[int] = field(default_factory=list)
    spec_ema: Optional[float] = None
    # interleaved chunked prefill (interleave_prefill): the slot is RESERVED
    # for an in-flight chunked admission — not yet decoding (active=False on
    # host AND device), but not free either. The admission record itself
    # lives in ``_chunk_admissions``; the flag keeps ``free_slots`` honest.
    prefilling: bool = False
    # flight-WAL watermark: how many of ``tokens`` have been journaled as
    # token_emit events (``_journal_emitted``); only the delta past it is
    # re-journaled each window, so the WAL carries each token once
    wal_mark: int = 0


class ContinuousEngine:
    """Owns the persistent slot state on device; NOT thread-safe by itself —
    the scheduler serializes all calls."""

    def __init__(
        self,
        config: LlamaConfig,
        params,
        sampling: SamplingConfig = SamplingConfig(),
        engine_config: EngineConfig = EngineConfig(),
        dtypes: DTypePolicy = DTypePolicy(),
        mesh: Optional[MeshContext] = None,
        pad_id: int = 0,
    ):
        self.config = config
        self.sampling = sampling
        self.engine_config = engine_config
        self.dtypes = dtypes
        self.mesh = mesh
        self.pad_id = pad_id
        self.B = engine_config.max_batch_size
        self.sync_steps = max(1, engine_config.decode_sync_steps)
        self.T = -(-engine_config.max_seq_len // 128) * 128
        # only buckets that leave decode room fit a slot; an empty ladder is
        # a config error — fail at construction, not per-request
        self.buckets = tuple(
            b for b in engine_config.prompt_buckets if b < self.T
        )
        if not self.buckets:
            raise ValueError(
                f"no prompt bucket in {engine_config.prompt_buckets} fits "
                f"max_seq_len={engine_config.max_seq_len} (slot length {self.T})"
            )
        jmesh = mesh.mesh if mesh is not None and mesh.tp > 1 else None
        if engine_config.kv_quant not in ("bf16", "int8"):
            raise ValueError(
                f"kv_quant={engine_config.kv_quant!r}: expected 'bf16' or 'int8'"
            )
        self.kv_quant = engine_config.kv_quant
        # ---- paged KV (block-pool arena; EngineConfig.kv_paged) ---------
        self.paged = bool(getattr(engine_config, "kv_paged", False))
        # ---- disaggregated pool role (ISSUE 20) -------------------------
        # "prefill" engines run admission only and hand each request's
        # pool blocks to a "decode"-role twin (export_request /
        # import_request); "unified" keeps single-replica behavior. Role
        # is POLICY, not capability: a prefill engine whose export fails
        # keeps decoding the request locally, so a disaggregated tier
        # degrades to unified instead of failing requests.
        engine_config.validate_pool_role()
        self.pool_role = engine_config.pool_role
        self.kv_pool: Optional[KVBlockPool] = None
        if self.paged:
            # tp>1 is served by the HEAD-SHARDED arena (each device holds
            # K/tp heads of every block; ops.attention.paged_partition_specs)
            # — the only constraint is that the kv-head count tiles the axis
            engine_config.validate_tp_layout(
                mesh.tp if mesh is not None else 1, config.num_kv_heads
            )
            bs = int(engine_config.kv_block_size)
            min_tile = 32 if self.kv_quant == "int8" else 16
            if bs < 1 or bs % min_tile:
                raise ValueError(
                    f"kv_block_size={bs} must be a positive multiple of the "
                    f"Mosaic {min_tile}-row tile (kv_quant={self.kv_quant!r})"
                )
            bad = [b for b in self.buckets if b % bs]
            if bad or self.T % bs:
                raise ValueError(
                    f"kv_block_size={bs} must divide every prompt bucket "
                    f"{self.buckets} and the slot length {self.T}"
                )
            # max logical blocks any row can hold (tables are [B, MB])
            self.MB = self.T // bs
            usable = int(engine_config.kv_pool_blocks) or self.B * self.MB
            if usable < self.MB:
                raise ValueError(
                    f"kv_pool_blocks={usable}: the pool must hold at least "
                    f"one full row ({self.MB} blocks of {bs})"
                )
            self.kv_pool = KVBlockPool(usable + 1, bs)  # +1: the null block
            self.block_size = bs
            self._tables_host = np.zeros((self.B, self.MB), np.int32)
            self._tables_dev = None
            self._tables_dirty = True
            self._slot_blocks: List[List[int]] = [[] for _ in range(self.B)]
            # block-granular prefix reuse: chain_key -> (full block ids,
            # covered tokens, prefix length); the pool holds one cache ref
            # per registered block so rows come and go copy-free
            self._prefix_blocks: "Dict[object, Tuple[List[int], int, int]]" = {}
            # covered tokens across registrations, maintained at every
            # register/evict site (all on the scheduler thread): the
            # fragmentation gauge's scrape-thread callback reads this ONE
            # int instead of iterating the dict the scheduler mutates
            self._registered_tokens = 0
            # admissions that mapped a registration's shared blocks since it
            # was (re-)registered — release_prestaged(only_unused=True)
            # keeps a registration live traffic has proven hot
            self._prefix_uses: Dict[object, int] = {}
            # hotness tier per registration (engine/tiering.py): admission
            # and growth pressure reclaim non-hot registrations FIRST (and
            # even while rows decode — a warm chunk's KV survives in the
            # prefix cache, one re-scatter away), so tier occupancy, not
            # raw headroom, decides backpressure
            self._prefix_tier: Dict[object, str] = {}
            # non-hot registered blocks right now — a single int the
            # admission gate's reclaimable hint reads LOCK-FREE from the
            # HTTP threads (maintained only on the scheduler thread)
            self._reclaimable_blocks = 0
            # registration GENERATION per chain key: a deferred lookahead
            # release presents the generation it staged, so it can never
            # free a registration a later admission re-created at the same
            # key (uses resets to 0 on re-registration — the counter alone
            # can't tell the two apart)
            self._prefix_reg_gen: Dict[object, int] = {}
            self._reg_seq = 0
            self._admit_seq = 0
            self._preempted: List[Tuple[int, List[int]]] = []
            self._blocks_at_retire: Dict[int, int] = {}
            # CHUNK-granular canonical registrations (reuse="chunk"):
            # seg_key -> (full block ids, canonical logical offset, segment
            # length, cache-entry creation stamp, tokens-counted flag).
            # Unlike _prefix_blocks (whole-chain sharing, copy-free), these
            # are the SOURCE blocks a per-chunk admission re-rotates into
            # freshly allocated destination blocks at arbitrary order —
            # content-safe at any position because K is position-shifted in
            # the copy. Stamp identity ties each registration to the
            # prefix-cache entry it mirrors, so a rebuilt entry silently
            # retires the stale registration (plan lookups decline on
            # mismatch). OrderedDict: plan hits move-to-end, so the cap
            # (PrefixCacheConfig.chunk_pool_regs) evicts least-recently-
            # PLANNED, not oldest-inserted.
            self._chunk_regs: "OrderedDict[str, tuple]" = OrderedDict()
            self._chunk_reg_tokens = 0
        # ---- speculative decoding (paged draft-and-verify; ISSUE 13) ----
        # Each sync window may run as ONE multi-token VERIFY step instead
        # of decode_sync_steps single-token steps: the host drafts up to
        # spec_K continuation tokens per row by prompt-lookup over the
        # row's own history (the retrieved chunks ARE the draft corpus —
        # no draft model), the device feeds last_tok + drafts through the
        # block tables in one chunked forward, and target-matching
        # acceptance keeps the longest prefix equal to what the vanilla
        # step would have sampled — greedy AND seeded streams stay
        # byte-identical by construction. docs/SPECULATIVE.md.
        self.spec_on = bool(getattr(engine_config, "spec_paged", False))
        # requests whose rows ever OFFERED drafts to a verify window — the
        # per-request approximation fingerprint's spec_verify source
        # (obs/shadow.py). Engine state, NOT the goodput ledger: turning
        # attribution accounting off must not erase audit fingerprints.
        # Popped at delivery / discard; bounded against never-delivered
        # rids by the discard sweep sharing the ledger's cleanup sites.
        self._spec_rids: set = set()
        if self.spec_on:
            if not self.paged:
                raise ValueError(
                    "spec_paged=True requires kv_paged=True — the verify "
                    "step writes drafted positions through block tables "
                    "(the dense continuous path does not speculate)"
                )
            self.spec_K = int(engine_config.spec_paged_tokens)
            if self.spec_K < 1:
                raise ValueError(
                    f"spec_paged_tokens={self.spec_K}: expected >= 1"
                )
            self.spec_ngram = max(1, int(engine_config.spec_ngram))
            self.spec_min_accept = float(engine_config.spec_paged_min_accept)
            if not 0.0 <= self.spec_min_accept <= 1.0:
                raise ValueError(
                    f"spec_paged_min_accept={self.spec_min_accept}: an "
                    "acceptance-RATE floor must lie in [0, 1]"
                )
        # ---- unified ragged sync windows (chunked prefill; ISSUE 16) ----
        # With interleave_prefill on, admission no longer prefills in one
        # phase-separated shot: admit_many RESERVES a row and queues a
        # chunked-admission record, and each mixed window feeds a budgeted
        # slice of pending prompts alongside every active decode lane
        # through ONE chunked forward (paged_chunk_attention's third
        # consumer, after prefix splicing and speculative verify). Streams
        # stay byte-identical to the phase-separated scheduler because
        # sampling is (seed, position)-keyed: the first token of a prompt
        # of length P folds fold_in(row_key, P) on its FINAL chunk exactly
        # as the one-shot admission does, and decode lanes fold wi+1
        # exactly as step_paged does — window shape cannot change draws.
        self.interleave_on = bool(
            getattr(engine_config, "interleave_prefill", False)
        )
        # in-flight chunked admissions, admission order (= scheduling
        # order; FIFO keeps TTFT fair). rid -> dict with the reserved row,
        # truncated prompt, progress frontier, UNFOLDED row key, decode
        # budget, admission stamps. Initialized unconditionally: reset(),
        # evict_requests and the planner touch it without re-checking the
        # knob.
        self._chunk_admissions: "OrderedDict[int, dict]" = OrderedDict()
        if self.interleave_on:
            engine_config.validate_interleave()  # requires kv_paged, ranges
            self.chunk_tokens = int(engine_config.prefill_chunk_tokens)
            self.window_budget = int(engine_config.window_token_budget) or (
                self.B + self.chunk_tokens
            )
        # ---- goodput ledger (obs/goodput.py; ISSUE 14) ------------------
        # every device sync window — admission prefills, decode windows,
        # verify windows — is attributed into the closed category set with
        # a per-request chip-second split; the scheduler pops each
        # request's figures at delivery (/generate timings), /metrics
        # reads the rolling totals, and each window journals ONE
        # goodput_window flight event so flightview --goodput reconstructs
        # the same report offline. Host-side dict math only; the
        # goodput_overhead bench leg holds it to <= 2% of decode steps/s.
        self.ledger = obs_goodput.ledger_for(config, engine_config)
        # request ids whose NEXT admission re-feeds tokens already computed
        # once (preemption / reset resubmission) — that admission's real
        # token lanes are attributed preempt_rework, exactly once (the
        # scheduler marks before requeueing; the admission pops)
        self._rework_rids: "set" = set()
        self.params, fused = maybe_fuse_params(params, engine_config, mesh)
        self.params, quantized = maybe_quantize_params(self.params, engine_config)
        self.model = LlamaModel(
            config, dtypes, attn_impl=engine_config.attn_impl, mesh=jmesh,
            fused_qkv=fused, quantized=quantized, kv_quant=self.kv_quant,
        )
        self.model_step = self.model.copy(row_frontier=True)
        # chunked variant for prefix-cache admissions: the suffix prefills
        # over a spliced cached-prefix block with offset causality
        self.model_chunked = self.model.copy(chunked=True)
        if self.paged:
            # paged variants: same static switches + the block-table arg
            self.model_step_paged = self.model.copy(row_frontier=True, paged=True)
            self.model_chunked_paged = self.model.copy(chunked=True, paged=True)
        self._compiled: Dict[Tuple[str, int, int], jax.stages.Compiled] = {}
        # ---- persistent device state -----------------------------------
        # the cache rides as a TUPLE pytree through every executable:
        # (k, v) bf16, or (k, v, k_scale, v_scale) with kv_quant="int8" —
        # the int8 payloads and fp32 scale planes donate/rebuild together
        self._cache = self._fresh_cache()
        # per-device arena residency, captured ONCE from the freshly built
        # planes (sharding is static: reset() rebuilds identical shapes, so
        # this never goes stale). The scrape-thread gauge reads this dict —
        # touching the LIVE planes there would race a step's donation and
        # crash /metrics with "Array has been deleted"
        self._arena_device_bytes: Dict[str, float] = {}
        if self.paged:
            for plane in self._cache:
                for sh in plane.addressable_shards:
                    did = str(getattr(sh.device, "id", 0))
                    self._arena_device_bytes[did] = (
                        self._arena_device_bytes.get(did, 0.0)
                        + float(sh.data.nbytes)
                    )
        self._kv_start = self._put(jnp.zeros((self.B,), jnp.int32))
        self._kv_len = self._put(jnp.zeros((self.B,), jnp.int32))
        self._last_tok = self._put(jnp.zeros((self.B,), jnp.int32))
        self._active = self._put(jnp.zeros((self.B,), bool))
        # per-row PRNG keys: a request's draws are keyed by its own seed and
        # token position, so they do not depend on its batchmates (solo vs
        # shared-batch runs of the same seeded request sample identically)
        self._rng_keys = self._put(jnp.zeros((self.B, 2), jnp.uint32))
        self._rng = jax.random.PRNGKey(sampling.seed)  # seedless-key stream
        # ---- host-side bookkeeping -------------------------------------
        self.slots = [_Slot() for _ in range(self.B)]
        self.steps = 0  # global decode steps executed (tests/metrics)
        self.stats = EngineStats()  # /metrics parity with InferenceEngine
        # observability handles (obs/metrics.py): standalone engines report
        # into the process default registry; RagService rebinds to its own
        self.bind_metrics(obs_metrics.default_registry())

    def bind_metrics(self, registry) -> None:
        """Point this engine's metric handles at ``registry``. Unlike the
        one-shot engine, the slot engine's host loop sees real per-request
        and per-window boundaries, so TTFT and inter-token latency here are
        measured EXACTLY (admission → first token; step window / k)."""
        self._obs = registry
        self._m_compile_events = registry.counter(
            "rag_compile_events_total", "AOT lowering/compile events"
        )
        self._m_compile_seconds = registry.counter(
            "rag_compile_seconds_total", "seconds spent in AOT lowering/compile"
        )
        self._m_ttft = registry.histogram(
            "rag_time_to_first_token_seconds",
            "submit-to-first-token (queue + coalesce + prefill + fetch)",
            buckets=obs_metrics.REQUEST_BUCKETS,
        )
        self._m_itl = registry.labeled_histogram(
            "rag_decode_inter_token_seconds",
            "per-decoded-token latency (mode label: oneshot_est is call "
            "duration over decode steps; continuous is exact per window)",
            buckets=obs_metrics.TOKEN_LATENCY_BUCKETS,
        ).labels(mode="continuous")
        # step-time breakdown (ISSUE 3 per-device telemetry): where one sync
        # window's wall clock goes — the device step + token-plane fetch
        # (phase=device_fetch), host retire bookkeeping (phase=host_drain),
        # and admission work between windows (phase=admit: prefill + insert
        # + first-token fetch for a whole admitted chunk). On a dashboard, a
        # growing device_fetch share under flat host_drain is link pressure;
        # a growing admit share is churn (short answers re-admitting).
        step_fam = registry.labeled_histogram(
            "rag_continuous_step_seconds",
            "continuous-engine step-time breakdown (phase label: "
            "device_fetch | host_drain | admit)",
            buckets=obs_metrics.LATENCY_BUCKETS,
        )
        self._m_step_device = step_fam.labels(phase="device_fetch")
        self._m_step_drain = step_fam.labels(phase="host_drain")
        self._m_step_admit = step_fam.labels(phase="admit")
        # paged KV pool occupancy (families exist in every mode so scrapes
        # and dashboards stay uniform; they read 0 under the dense cache)
        pool = self.kv_pool
        registry.labeled_gauge(
            "rag_kv_pool_blocks_total",
            "allocatable physical KV blocks (paged mode; 0 dense)",
        ).labels_callback(
            lambda: float(pool.usable_blocks()) if pool is not None else 0.0
        )
        registry.labeled_gauge(
            "rag_kv_pool_blocks_in_use",
            "physical KV blocks currently referenced (paged mode)",
        ).labels_callback(
            lambda: float(pool.blocks_in_use()) if pool is not None else 0.0
        )
        registry.labeled_gauge(
            "rag_kv_pool_fragmentation",
            "fraction of allocated KV token slots not holding live KV "
            "(internal fragmentation — pad/tail waste of the block layout)",
        ).labels_callback(
            lambda: (
                pool.fragmentation(self.pool_used_tokens())
                if pool is not None else 0.0
            )
        )
        self._m_pool_preempt = registry.counter(
            "rag_kv_pool_preemptions_total",
            "rows preempted mid-decode by pool exhaustion (resubmitted by "
            "the scheduler; callers see latency, not errors)",
        )
        # per-device arena residency (tp triage: head-sharded arenas show
        # ~total/tp per chip — a device whose share diverges is holding
        # something else). Values come from the construction-time static
        # dict, never the live planes (see __init__)
        dev_fam = registry.labeled_gauge(
            "rag_kv_pool_device_bytes",
            "paged KV arena bytes resident per device (head-sharded over "
            "tp: ~arena_total/tp per chip; 0 under the dense cache)",
        )
        for did in sorted(self._arena_device_bytes) or ["0"]:
            dev_fam.labels_callback(
                lambda did=did: self._arena_device_bytes.get(did, 0.0),
                device=did,
            )
        # (the rag_spec_tokens_total / rag_spec_acceptance_rate families
        # are registered by the SERVICE — server/app.py — off the shared
        # EngineStats fields, so they exist uniformly in every serving
        # mode; standalone engines expose the same numbers via .stats)

    def warmup(self, batch_sizes=None, buckets=None):
        """AOT-compile every executable serving will hit (readiness gating).
        ``batch_sizes`` here sizes the ADMISSION-group ladder (rounded to
        powers of two): a scheduler that admits queued requests in groups
        should warm the group sizes it will use, or the first burst pays a
        mid-serving compile. Slot geometry itself is fixed at construction."""
        sizes = {1}
        for b in batch_sizes or (1,):
            n = 1
            while n * 2 <= min(max(1, b), self.B):
                n *= 2
                sizes.add(n)  # the WHOLE pow2 ladder: admit_many splits
                # arbitrary group sizes into pow2 chunks, so every rung
                # below the cap is reachable at runtime
        for S in buckets or self.buckets:
            if S not in self.buckets:
                continue  # admit can never use a bucket without decode room
            for n in sorted(sizes):
                if self.paged:
                    self._get("prefill_paged", S, n)
                    self._get("insert_paged", S, n)
                else:
                    self._get("prefill", S, n)
                    self._get("insert", S, n)
        self._get("step_paged" if self.paged else "step", self.sync_steps)
        if self.spec_on:
            # the verify executable AND the plain window both serve under
            # speculation (windows where no row drafts fall back), so warm
            # both — the first quoting answer must not pay a compile
            self._get("verify_paged", self.spec_K)
        if self.interleave_on:
            # the mixed decode+chunk window — the first interleaved
            # admission must not pay a compile either
            self._get("mixed_step", self.chunk_tokens)

    def _put(self, x, sharding=None):
        """Place a host/device value to match a lowered aval's sharding;
        identity off-mesh."""
        if self.mesh is None:
            return jnp.asarray(x)
        return jax.device_put(x, sharding or self.mesh.replicated)

    def _fresh_cache(self):
        """The cache-state tuple for the full [B, T] slot block (__init__).
        On a mesh the zeros are built DIRECTLY sharded (jit with
        out_shardings) — materializing the full cache on one device and
        resharding would transiently need tp× the steady per-chip footprint,
        an OOM risk at construction and at every post-failure reset."""

        def build():
            if self.paged:
                cache = make_kv_arena(
                    self.config, self.kv_pool.num_blocks, self.block_size,
                    self.dtypes.compute_dtype, quant=self.kv_quant,
                )
            else:
                cache = make_kv_cache(
                    self.config, self.B, self.T, self.dtypes.compute_dtype,
                    quant=self.kv_quant,
                )
            if self.kv_quant == "int8":
                return (cache.k, cache.v, cache.k_scale, cache.v_scale)
            return (cache.k, cache.v)

        if self.mesh is None:
            return build()
        return jax.jit(build, out_shardings=self._cache_shardings())()

    def reset(self):
        """Rebuild ALL device state after a failed step. A step that dies
        during device execution has already invalidated its DONATED inputs
        (cache, kv_len, last_tok, active) — merely deactivating slots would
        leave the next admit holding deleted arrays, bricking the engine
        while /healthz still reports ready."""
        flight.emit(
            "reset",
            in_flight=sum(1 for s in self.slots if s.active),
        )
        self.slots = [_Slot() for _ in range(self.B)]
        self._cache = self._fresh_cache()
        self._kv_start = self._put(jnp.zeros((self.B,), jnp.int32))
        self._kv_len = self._put(jnp.zeros((self.B,), jnp.int32))
        self._last_tok = self._put(jnp.zeros((self.B,), jnp.int32))
        self._active = self._put(jnp.zeros((self.B,), bool))
        self._rng_keys = self._put(jnp.zeros((self.B, 2), jnp.uint32))
        if self.paged:
            # every block back to the free list: the arena was rebuilt, so a
            # ref held across reset() would leak the pool one reset at a
            # time (make chaos asserts zero leaked blocks after recovery)
            self.kv_pool.reset()
            self._tables_host[:] = NULL_BLOCK
            self._tables_dirty = True
            self._slot_blocks = [[] for _ in range(self.B)]
            self._prefix_blocks.clear()
            self._prefix_uses.clear()
            self._prefix_reg_gen.clear()
            self._prefix_tier.clear()
            self._reclaimable_blocks = 0
            self._registered_tokens = 0
            # chunk registrations' blocks went back with kv_pool.reset()
            self._chunk_regs.clear()
            self._chunk_reg_tokens = 0
            # pending preemption records describe PRE-reset slots; the reset
            # recovery resubmits every in-flight request itself, so replaying
            # a stale record would double-submit it (duplicate tokens at the
            # stream head + a full duplicate decode)
            self._preempted.clear()
            # same story for in-flight chunked admissions: their blocks went
            # back with kv_pool.reset(), and the reset recovery resubmits
            # the requests — a stale record would re-prefill into a row the
            # resubmission also claims
            self._chunk_admissions.clear()

    # ------------------------------------------------------------------
    # executables
    # ------------------------------------------------------------------
    def _get(self, kind: str, S: int, n: int = 1):
        key = (kind, S, n)
        fn = self._compiled.get(key)
        if fn is None:
            t0 = time.perf_counter()
            if kind == "step":
                fn = self._build_step(S)  # S carries the sync window here
            elif kind == "step_paged":
                fn = self._build_step_paged(S)
            elif kind == "prefill":
                fn = self._build_prefill(S, n)
            elif kind == "prefill_paged":
                fn = self._build_prefill_paged(S, n)
            elif kind == "insert_paged":
                fn = self._build_insert_paged(S, n)
            elif kind == "prefill_px":
                fn = self._build_prefill_prefixed(S, n)  # n carries the suffix bucket
            elif kind == "prefill_px_paged":
                fn = self._build_prefill_px_paged(S)  # S carries the suffix bucket
            elif kind == "prefix_scatter":
                fn = self._build_prefix_scatter(S)  # S carries the buffer width
            elif kind == "chunk_splice":
                fn = self._build_chunk_splice(S)  # S carries the block count
            elif kind == "migrate_out":
                fn = self._build_migrate_out(S)  # S carries the block count
            elif kind == "migrate_in":
                fn = self._build_migrate_in(S)  # S carries the block count
            elif kind == "boundary_px":
                fn = self._build_boundary_px_paged(S)  # S carries the window
            elif kind == "verify_paged":
                fn = self._build_verify_paged(S)  # S carries the draft count K
            elif kind == "mixed_step":
                fn = self._build_mixed_step(S)  # S carries the chunk width
            else:
                fn = self._build_insert(S, n)
            self._m_compile_events.inc()
            self._m_compile_seconds.inc(time.perf_counter() - t0)
            self._compiled[key] = fn
        return fn

    def _shardings(self):
        """(cache_payload, cache_scale, replicated) NamedShardings — or all
        None off-mesh. The cache shards its kv-head axis over tp (matching
        the attention kernels' shard_map specs) when head counts divide;
        everything host-fed is replicated. The SAME specs serve both
        layouts: the dense ``[L, B, K, T, hd]`` cache and the paged
        ``[L, N, K, bs, hd]`` arena put kv heads at dim 2 (and the scale
        planes drop the trailing hd either way), so the head-sharded arena
        is spec-identical to the dense tp cache. Executables are lowered
        with and return EXACTLY these, so state tuples round-trip between
        prefill → insert → step without 'sharding does not match'
        rejections (an unsharded lowering bricks every request on a tp>1
        mesh)."""
        if self.mesh is None:
            return None, None, None
        rep = self.mesh.replicated
        K, tp = self.config.num_kv_heads, self.mesh.tp
        if tp > 1 and K % tp == 0:
            return (
                self.mesh.sharding(None, None, "tp", None, None),
                self.mesh.sharding(None, None, "tp", None),
                rep,
            )
        return rep, rep, rep

    def _cache_shardings(self):
        """Per-plane shardings for the cache-state tuple (None off-mesh)."""
        pay, sc, _ = self._shardings()
        if self.kv_quant == "int8":
            return (pay, pay, sc, sc)
        return (pay, pay)

    def _arena_shardings(self):
        """Per-plane shardings for the PAGED arena tuple — identical to the
        dense cache's (``_shardings``: kv heads at dim 2 in both layouts),
        aliased for call-site clarity."""
        return self._cache_shardings()

    def _cache_avals(self, batch: int, length: int):
        """ShapeDtypeStructs (with shardings, on-mesh) for the cache tuple."""
        L, K, hd = self.config.num_layers, self.config.num_kv_heads, self.config.head_dim
        cdt = jnp.int8 if self.kv_quant == "int8" else self.dtypes.compute_dtype
        shardings = self._cache_shardings()
        payload = jax.ShapeDtypeStruct(
            (L, batch, K, length, hd), cdt, sharding=shardings[0]
        )
        if self.kv_quant == "int8":
            scale = jax.ShapeDtypeStruct(
                (L, batch, K, length), jnp.float32, sharding=shardings[2]
            )
            return (payload, payload, scale, scale)
        return (payload, payload)

    def _build_prefill(self, S: int, n: int = 1):
        """``n`` requests prefill together into fresh S-length row caches —
        batched admission amortizes the per-admission dispatch + first-token
        fetch (decisive on a slow host link: one round-trip per GROUP).
        Per-row pre-folded keys keep draws (seed, position)-deterministic
        regardless of the admission grouping."""
        cfg, dt, sampling = self.config, self.dtypes, self.sampling
        model = self.model
        kv_quant = self.kv_quant

        def prefill(params, tokens, pad_mask, rngs):
            cache = make_kv_cache(cfg, n, S, dt.compute_dtype, quant=kv_quant)
            kv_start, _ = mask_window(pad_mask)
            positions = jnp.clip(jnp.cumsum(pad_mask, axis=-1) - 1, 0)
            logits, cache = model.apply(
                {"params": params}, tokens, positions, cache,
                kv_start, jnp.full((n,), S, jnp.int32), jnp.int32(0),
                last_logit_only=True,
            )
            tok0 = sample_token_per_row(rngs, logits[:, -1], sampling)
            rows = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kv_quant == "int8" else (cache.k, cache.v)
            )
            return rows, tok0, kv_start

        rep = self.mesh.replicated if self.mesh is not None else None
        # pin output shardings so the row block arrives EXACTLY as insert's
        # lowered avals expect it (unpinned propagation can pick a different
        # layout and insert would reject the mismatch at call time)
        out_shardings = (
            (self._cache_shardings(), rep, rep) if self.mesh is not None else None
        )
        return jax.jit(prefill, out_shardings=out_shardings).lower(
            param_avals(self.params),
            jax.ShapeDtypeStruct((n, S), jnp.int32, sharding=rep),
            jax.ShapeDtypeStruct((n, S), jnp.int32, sharding=rep),
            jax.ShapeDtypeStruct((n, 2), jnp.uint32, sharding=rep),
        ).compile()

    def _build_prefill_prefixed(self, S: int, C: int):
        """Batch-1 PREFIXED admission (KV prefix cache): splice a
        ``CachedPrefix`` block into a fresh left-padded ``S``-slot row cache
        and prefill only the ``C``-bucketed suffix — the row block then goes
        through the ordinary ``_insert`` executable, which already accepts
        pre-populated KV rows (it splices whatever row planes it is handed).

        Slot geometry: the row's tokens end at slot ``S`` (left padding), so
        the prefix block lands at ``start = S - total`` and the suffix
        chunk-prefills at ``start + prefix_len``. Positions stay canonical
        (0-based) — RoPE is baked into the cached K by position, not slot.
        """
        cfg, dt, sampling = self.config, self.dtypes, self.sampling
        mc = self.model_chunked
        kv_quant = self.kv_quant
        P = self.engine_config.prefix_cache.max_prefix_tokens
        # the splice buffer is P wide and lands as low as slot 0, and the
        # suffix write spans [start + prefix_len, start + prefix_len + C):
        # size the build cache so neither dynamic_update_slice can clamp
        # (a clamped start silently shifts the block over valid KV)
        T_build = -(-(S + P + C) // 128) * 128
        i32 = jnp.int32
        from rag_llm_k8s_tpu.models.llama import KVCache

        def prefill(params, suffix_tokens, suffix_len, ctx, prefix_len, rngs):
            cache = make_kv_cache(cfg, 1, T_build, dt.compute_dtype, quant=kv_quant)
            planes = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kv_quant == "int8" else (cache.k, cache.v)
            )
            plen = prefix_len.astype(i32)
            slen = suffix_len.astype(i32)
            total = plen + slen
            start = (S - total).astype(i32)
            planes = tuple(
                jax.lax.dynamic_update_slice(
                    c, b.astype(c.dtype),
                    (0, 0, 0, start) + ((0,) if c.ndim == 5 else ()),
                )
                for c, b in zip(planes, ctx)
            )
            positions = (plen + jnp.arange(C, dtype=i32))[None, :]
            kv_start = jnp.broadcast_to(start, (1,))
            # real tokens end exactly at slot S; right-padded suffix K/V
            # beyond lands at >= S and is dropped by the row slice below
            logits, cache = mc.apply(
                {"params": params}, suffix_tokens, positions, KVCache(*planes),
                kv_start, jnp.full((1,), S, i32), start + plen,
                logit_index=slen - 1,
            )
            tok0 = sample_token_per_row(rngs, logits[:, -1], sampling)
            out = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kv_quant == "int8" else (cache.k, cache.v)
            )
            rows = tuple(c[:, :, :, :S] for c in out)
            return rows, tok0, kv_start

        rep = self.mesh.replicated if self.mesh is not None else None
        ctx_avals = tuple(
            jax.ShapeDtypeStruct(shape, dtype, sharding=rep)
            for shape, dtype in self._prefix_plane_shapes(P)
        )
        out_shardings = (
            (self._cache_shardings(), rep, rep) if self.mesh is not None else None
        )
        return jax.jit(prefill, out_shardings=out_shardings).lower(
            param_avals(self.params),
            jax.ShapeDtypeStruct((1, C), jnp.int32, sharding=rep),
            jax.ShapeDtypeStruct((), jnp.int32, sharding=rep),
            ctx_avals,
            jax.ShapeDtypeStruct((), jnp.int32, sharding=rep),
            jax.ShapeDtypeStruct((1, 2), jnp.uint32, sharding=rep),
        ).compile()

    def _prefix_plane_shapes(self, length: int):
        """(shape, dtype) per prefix-buffer plane — mirrors the one-shot
        engine's layout so CachedPrefix descriptors are interchangeable."""
        c = self.config
        cdt = jnp.int8 if self.kv_quant == "int8" else self.dtypes.compute_dtype
        pay = ((c.num_layers, 1, c.num_kv_heads, length, c.head_dim), cdt)
        out = [pay, pay]
        if self.kv_quant == "int8":
            sc = ((c.num_layers, 1, c.num_kv_heads, length), jnp.float32)
            out += [sc, sc]
        return out

    def admit_prefixed(
        self,
        request_id: int,
        suffix: Sequence[int],
        prefix,  # CachedPrefix (engine/prefix_cache.py)
        max_new: int,
        seed: Optional[int] = None,
    ) -> Tuple[int, Optional[List[int]]]:
        """Admit one request whose prompt head is a cached prefix: only the
        suffix prefills; the prefix KV splices from the descriptor. Same
        return contract as ``admit``. Raises ValueError when the shapes
        don't fit a slot (caller falls back to a plain admission)."""
        free = self.free_slots()
        assert free, "admit_prefixed() without a free slot"
        if not suffix:
            # logit_index would clip to a PAD position — see generate_prefixed
            raise ValueError("admit_prefixed needs a non-empty suffix")
        pc = getattr(self.engine_config, "prefix_cache", None)
        if pc is None or prefix.capacity != pc.max_prefix_tokens:
            raise ValueError("prefix descriptor does not match this engine's config")
        total = prefix.length + len(suffix)
        S = bucket_len(max(total, 1), self.buckets)
        if total > S:
            raise ValueError(
                f"prefixed prompt of {total} tokens exceeds the largest "
                f"continuous bucket {S}"
            )
        if len(suffix) > max(pc.suffix_buckets):
            raise ValueError(
                f"prefixed suffix of {len(suffix)} tokens exceeds the "
                f"largest suffix bucket {max(pc.suffix_buckets)}"
            )
        C = bucket_len(max(len(suffix), 1), pc.suffix_buckets)
        max_new_c = max(1, min(max_new, self.T - S))
        if seed is not None:
            row_key = jax.random.PRNGKey(seed)
        else:
            self._rng, row_key = jax.random.split(self._rng)
        folded = jax.random.fold_in(row_key, total)[None, :]

        toks = np.full((1, C), self.pad_id, np.int32)
        toks[0, : len(suffix)] = list(suffix)
        row = free[0]
        if self.paged:
            return self._admit_prefixed_paged(
                request_id, suffix, prefix, C, max_new_c, row, row_key,
                folded, toks,
            )
        t_admit = time.perf_counter()
        row_cache, tok0s, row_starts = self._get("prefill_px", S, C)(
            self.params, self._put(toks), self._put(jnp.int32(len(suffix))),
            tuple(self._put(p) for p in prefix.planes),
            self._put(jnp.int32(prefix.length)), self._put(folded),
        )
        try:
            (self._cache, self._kv_start, self._kv_len,
             self._last_tok, self._active, self._rng_keys) = self._get("insert", S, 1)(
                self._cache, row_cache,
                self._kv_start, self._kv_len, self._last_tok, self._active,
                self._rng_keys, self._put(np.asarray([row], np.int32)),
                row_starts, tok0s, self._put(row_key[None, :]),
            )
        except BaseException as e:  # noqa: BLE001 — same contract as _admit_chunk
            self.reset()
            raise EngineStateLost("insert failed; engine state reset") from e
        tok0 = int(np.asarray(tok0s)[0])
        self.stats.generate_calls += 1
        self.stats.prefill_tokens += len(suffix)
        self.stats.prefill_tokens_skipped += int(prefix.length)
        flight.emit(
            "admit", request_id, slot=row, prompt_len=total,
            prefix_len=int(prefix.length), tok0=tok0,
            **_tenant_attr(self.ledger, request_id),
        )
        self._journal_window(self.ledger.record_prefill_px(
            time.perf_counter() - t_admit, bucket=C, rid=request_id,
            computed=len(suffix), skipped=int(prefix.length),
            rework=bool(self._take_rework((request_id,))),
        ))
        if tok0 in self.config.eos_token_ids or max_new_c <= 1:
            out = [] if tok0 in self.config.eos_token_ids else [tok0]
            self.stats.decode_tokens += len(out)
            m = np.ones(self.B, bool)
            m[row] = False
            self._active = self._active & self._put(jnp.asarray(m))
            return row, out
        self.slots[row] = _Slot(
            request_id=request_id, tokens=[tok0], remaining=max_new_c - 1,
            active=True,
        )
        self.stats.decode_tokens += 1
        return row, None

    def _admit_prefixed_paged(
        self, request_id, suffix, prefix, C, max_new_c, row, row_key,
        folded, toks,
    ):
        """Paged tail of ``admit_prefixed``: block-granular prefix reuse.

        Shared FULL blocks of a previously-seen prefix (keyed by the
        descriptor's ``chain_key`` — set only under exact-chain reuse, the
        policy whose cached KV is bit-faithful to a cold prefill) map into
        the row's table copy-free, pinned by a pool ref; only the partial
        tail block scatters from the descriptor's splice buffer, and only
        the suffix prefills — a paged chunk straight into pool blocks. A
        first sighting scatters the whole prefix and REGISTERS its full
        blocks (one cache ref each), so the next request with the same
        prompt head shares them without copying a byte."""
        t_admit = time.perf_counter()
        bs = self.block_size
        plen = int(prefix.length)
        slen = len(suffix)
        total = plen + slen
        P = int(prefix.capacity)
        if P % bs:
            raise ValueError(
                f"prefix capacity {P} not a multiple of kv_block_size {bs}"
            )
        key = getattr(prefix, "chain_key", None)
        shared_ids: List[int] = []
        if key is not None:
            entry = self._prefix_blocks.get(key)
            if entry is not None and entry[2] == plen:
                shared_ids = list(entry[0])
                self._prefix_uses[key] = self._prefix_uses.get(key, 0) + 1
        # chunk-granular assembly (reuse="chunk"): when the whole chain has
        # no shared registration but every chunk has a canonical one, the
        # block table assembles from per-chunk registrations at arbitrary
        # order — gather + RoPE-re-rotate into fresh blocks + boundary
        # re-prefill straight into pool blocks, no splice-buffer scatter
        plan = None
        if not shared_ids:
            plan = self._chunk_splice_plan(prefix)
        covered = len(shared_ids)
        need_total = self.kv_pool.blocks_for(max(total, 1))
        priv = self.kv_pool.alloc(need_total - covered)  # PoolExhausted → caller
        if shared_ids:
            self.kv_pool.ref(shared_ids)  # the row's own pin
        ids_all = shared_ids + priv
        self._assign_row_blocks(row, ids_all)
        self._device_tables()

        # scatter the un-shared prefix slabs (all of them on a miss; just
        # the partial tail block on a hit) from the splice buffer — unless
        # the per-chunk assembly path populates the blocks instead
        nbp = P // bs
        scatter_ids = np.zeros((nbp,), np.int32)
        if plan is None:
            for j in range(covered, min(self.kv_pool.blocks_for(plen), nbp)):
                scatter_ids[j] = ids_all[j]
        try:
            if plan is not None:
                self._chunk_splice_into_row(row, ids_all, plan)
            elif scatter_ids.any():
                self._cache = self._get("prefix_scatter", P, 0)(
                    self._cache, tuple(self._put(p) for p in prefix.planes),
                    self._put(jnp.asarray(scatter_ids)),
                )
            self._cache, tok0s = self._get("prefill_px_paged", C, 0)(
                self.params, self._cache,
                self._put(jnp.asarray(self._tables_host[row : row + 1])),
                self._put(toks), self._put(jnp.int32(slen)),
                self._put(jnp.int32(plen)), self._put(folded),
            )
        except BaseException as e:  # noqa: BLE001 — donated arena invalidated
            self.reset()
            raise EngineStateLost("prefixed insert failed; engine state reset") from e

        # register a first-seen prefix's full blocks for future sharing —
        # from the scatter path AND the per-chunk assembly (a repeated
        # permutation must map these blocks copy-free, not re-splice and
        # re-run its boundary prefills on every admission)
        full_n = plen // bs
        shared_tok = covered * bs  # tokens this row serves from shared blocks
        chain_registered = key is not None and not shared_ids and full_n > 0
        if chain_registered:
            reg = ids_all[:full_n]
            self.kv_pool.ref(reg)  # the cache's own ref outlives the row
            self._register_prefix(key, reg, plen)
            shared_tok = full_n * bs  # now registration-counted, not row-counted
        if plan is None and not shared_ids:
            # block-aligned exact spans become per-chunk canonical copies
            # (reuse="chunk" metadata only; no-op otherwise). Scatter
            # admissions ONLY: on a chain hit the blocks hold an EARLIER
            # admission's content — this resolve's span exactness/stamps
            # do not describe those bytes, and registering them could
            # canonicalize a re-rotated copy (compounding drift)
            self._register_chunks_from_scatter(
                prefix, ids_all, chain_registered=chain_registered
            )

        tok0 = int(np.asarray(tok0s)[0])
        self._kv_len = self._kv_len.at[row].set(total)
        self._last_tok = self._last_tok.at[row].set(tok0)
        self._rng_keys = self._rng_keys.at[row].set(self._put(row_key))
        self.stats.generate_calls += 1
        self.stats.prefill_tokens += slen
        self.stats.prefill_tokens_skipped += plen
        flight.emit(
            "admit", request_id, slot=row, prompt_len=total, prefix_len=plen,
            shared=shared_tok, tok0=tok0,
            **_tenant_attr(self.ledger, request_id),
        )
        self._journal_window(self.ledger.record_prefill_px(
            time.perf_counter() - t_admit, bucket=C, rid=request_id,
            computed=slen, skipped=plen,
            rework=bool(self._take_rework((request_id,))),
        ))
        if tok0 in self.config.eos_token_ids or max_new_c <= 1:
            out = [] if tok0 in self.config.eos_token_ids else [tok0]
            self.stats.decode_tokens += len(out)
            self._blocks_at_retire[request_id] = len(self._slot_blocks[row])
            self._release_row(row)
            return row, out
        self._active = self._active.at[row].set(True)
        self._admit_seq += 1
        self.slots[row] = _Slot(
            request_id=request_id, tokens=[tok0], remaining=max_new_c - 1,
            active=True, kv_ub=total, admit_seq=self._admit_seq,
            prompt_len=total, shared_tokens=shared_tok,
            # spec draft corpus: a prefixed admission only carries the
            # SUFFIX token ids (the prefix is a KV descriptor — its ids
            # never reach the engine), so the corpus starts there and
            # grows with the emitted stream; drafting still fires on
            # self-repeats, just without the spliced context's text
            history=(list(suffix) + [tok0]) if self.spec_on else [],
        )
        self.stats.decode_tokens += 1
        return row, None

    def prestage_prefix(self, prefix, tier: str = "hot") -> "str | bool":
        """Warm a ``CachedPrefix``'s full blocks into POOL blocks ahead of
        any admission (the lookahead pipeline's paged leg — rag/lookahead):
        allocate ``length // block_size`` blocks, scatter the prefix planes
        into them, and REGISTER them under the chain key, so the first
        admission with this prompt head maps them copy-free instead of
        scattering — exactly the sharing ``_admit_prefixed_paged`` sets up
        on a first sighting, moved off the request path.

        Must be called from the engine's owning (dispatcher) thread —
        ``ContinuousScheduler.run_on_engine`` is the safe entry. Headroom-
        gated: never takes blocks unless a full row's growth stays free, so
        pre-staging cannot starve live admissions. Returns ``"registered"``
        when THIS call created the registration (the caller owns the later
        release), ``"resident"`` when it already existed (an earlier
        admission or prestage owns it — never release someone else's), and
        False when nothing was staged."""
        if not self.paged:
            return False
        if tier == "cold":
            # a cold REGISTRATION must not exist (cold = not in the pool;
            # set_prefix_tier drops on cold for the same reason) — and the
            # prestage itself is evidence the chain is about to be used,
            # so register it reclaimable-but-resident
            tier = "warm"
        if tier not in ("hot", "warm"):
            raise ValueError(f"prestage tier={tier!r}: expected hot|warm|cold")
        key = getattr(prefix, "chain_key", None)
        if key is None:  # "slot"-mode prefixes are not content-identical
            return False
        pc = getattr(self.engine_config, "prefix_cache", None)
        if pc is None or prefix.capacity != pc.max_prefix_tokens:
            return False
        bs = self.block_size
        P = int(prefix.capacity)
        plen = int(prefix.length)
        full_n = plen // bs
        if P % bs or full_n <= 0 or full_n > P // bs:
            return False
        entry = self._prefix_blocks.get(key)
        if entry is not None and entry[2] == plen:
            return "resident"  # earlier admission or prestage owns it
        if not self.kv_pool.can_alloc(full_n + self.MB):
            return False  # headroom: live traffic keeps a full row's growth
        ids = self.kv_pool.alloc(full_n)
        try:
            # fault site "kv_swap_in": a cold chain's host→HBM re-stage
            # dying between alloc and scatter. Nothing was scattered and
            # nothing donated — free the blocks and decline, and the
            # admission path recomputes from tokens (zero leaked blocks;
            # distinct from a real scatter failure below, which invalidates
            # the donated arena and must reset)
            faults.maybe_fail("kv_swap_in")
        except faults.InjectedFault:
            self.kv_pool.free(ids)
            return False
        nbp = P // bs
        scatter_ids = np.zeros((nbp,), np.int32)
        scatter_ids[:full_n] = ids
        try:
            self._cache = self._get("prefix_scatter", P, 0)(
                self._cache, tuple(self._put(p) for p in prefix.planes),
                self._put(jnp.asarray(scatter_ids)),
            )
        except BaseException as e:  # noqa: BLE001 — donated arena invalidated
            self.reset()  # reset() reclaims ids with everything else
            raise EngineStateLost(
                "prefix prestage failed; engine state reset"
            ) from e
        # alloc()'s ref IS the registration ref (no row holds these yet) —
        # every reclaim path goes through _drop_registration, so
        # registrations free exactly once. ``tier`` (from the prefix
        # cache's hotness) decides how readily admission reclaims it.
        self._register_prefix(key, ids, plen, tier=tier)
        return "registered"

    def prestage_gen(self, chain_key):
        """The live registration generation for a chain (None when not
        registered) — a deferred release records it at staging time and
        presents it back (``release_prestaged(gen=)``), so it can never
        free a registration a later admission re-created at the same key.
        Same thread contract as ``prestage_prefix``."""
        return self._prefix_reg_gen.get(chain_key)

    def _register_prefix(self, key, ids, plen: int, tier: str = "hot") -> int:
        """Register a chain's full blocks for future copy-free sharing and
        return the registration generation; enforces the bounded-8 set.
        The caller has already taken the registration's pool ref. ``tier``
        is the chain's hotness class — non-hot registrations are the first
        blocks admission reclaims under pressure."""
        self._reg_seq += 1
        cov = len(ids) * self.block_size
        self._prefix_blocks[key] = (list(ids), cov, plen)
        self._prefix_uses[key] = 0
        self._prefix_reg_gen[key] = self._reg_seq
        self._prefix_tier[key] = tier
        self.kv_pool.account_tier(tier, len(ids))
        if tier != "hot":
            self._reclaimable_blocks += len(ids)
        self._registered_tokens += cov
        while len(self._prefix_blocks) > 8:  # bounded registration set
            self._drop_registration(next(iter(self._prefix_blocks)))
        return self._reg_seq

    def _drop_registration(self, key) -> bool:
        """The one place a registration dies: pops every side table, fixes
        the fragmentation counter, returns the blocks to the pool."""
        entry = self._prefix_blocks.pop(key, None)
        if entry is None:
            return False
        self._prefix_uses.pop(key, None)
        self._prefix_reg_gen.pop(key, None)
        ids, cov, _ = entry
        tier = self._prefix_tier.pop(key, "hot")
        self.kv_pool.account_tier(tier, -len(ids))
        if tier != "hot":
            self._reclaimable_blocks = max(
                0, self._reclaimable_blocks - len(ids)
            )
        self._registered_tokens -= cov
        self.kv_pool.free(ids)
        return True

    def set_prefix_tier(self, chain_key, tier: str) -> bool:
        """Move a registration between hotness tiers (scheduler thread —
        the service's retier maintenance arrives via ``run_on_engine``).
        ``"cold"`` DROPS the registration: a cold chain's arena blocks go
        back to the pool and its KV survives only in the prefix cache's
        host spill, one prestage re-scatter away (the pool-side spill).
        Returns True when anything changed."""
        if not self.paged:
            return False
        entry = self._prefix_blocks.get(chain_key)
        if entry is None:
            return False
        if tier == "cold":
            return self._drop_registration(chain_key)
        old = self._prefix_tier.get(chain_key, "hot")
        if old == tier:
            return False
        n = len(entry[0])
        self.kv_pool.account_tier(old, -n)
        self.kv_pool.account_tier(tier, n)
        self._prefix_tier[chain_key] = tier
        if old == "hot" and tier != "hot":
            self._reclaimable_blocks += n
        elif old != "hot" and tier == "hot":
            self._reclaimable_blocks = max(0, self._reclaimable_blocks - n)
        return True

    def retier_registrations(self, tier_fn) -> int:
        """Re-tag every registered chain with ``tier_fn(chain_key)`` — the
        cache→pool tier mirror (the service passes the prefix cache's
        ``chain_tier``; scheduler thread via ``run_on_engine``). A chain
        judged "cold" drops its registration. Returns how many
        registrations changed. Keeps the registration table behind the
        engine's API — callers never touch ``_prefix_blocks``."""
        if not self.paged:
            return 0
        changed = 0
        for key in list(self._prefix_blocks):
            if self.set_prefix_tier(key, tier_fn(key)):
                changed += 1
        return changed

    def tier_occupancy(self) -> Dict[str, int]:
        """Registered-block tier ledger + live-row blocks (the pool's
        view; empty dict dense). Reading the POOL's lock-guarded ledger is
        scrape-safe from any thread."""
        if not self.paged:
            return {}
        return self.kv_pool.tier_occupancy()

    def reclaimable_blocks(self) -> int:
        """Non-hot registered blocks the scheduler can reclaim without
        touching a live row — the admission gate's tier-occupancy signal
        (lock-free read of a scheduler-maintained int): while this is
        positive, a saturated pool is NOT a shed — the next admission
        sweep frees these and the request only queues."""
        if not self.paged:
            return 0
        return self._reclaimable_blocks

    def release_prestaged(self, chain_key, only_unused: bool = False,
                          gen=None) -> bool:
        """Stale-prefetch cancellation, pool side: drop one registered
        chain and free its blocks (ref-count-correct — rows still decoding
        over shared copies hold their own refs, so the pool only reclaims
        the registration's). ``only_unused=True`` keeps a registration an
        admission has mapped since it was staged — live traffic proved the
        speculation right, so the lookahead release must not cost future
        sharing. ``gen`` (from ``prestage_gen`` at staging time) guards the
        deferred-release race: if the staged registration was evicted and a
        later admission re-created one at this key, the generations differ
        and the admission's registration survives. Same thread contract as
        ``prestage_prefix``."""
        if not self.paged:
            return False
        if gen is not None and self._prefix_reg_gen.get(chain_key) != gen:
            return False  # a re-created registration owns this key now
        if only_unused and self._prefix_uses.get(chain_key, 0) > 0:
            return False
        return self._drop_registration(chain_key)

    def _build_insert(self, S: int, n: int = 1):
        """Splice ``n`` freshly prefilled row blocks + their per-row state
        into arbitrary slots in ONE device call (the admission group's
        counterpart to the batched prefill)."""

        def insert(cache, row_cache, kv_start, kv_len, last_tok, active,
                   rng_keys, rows, row_starts, tok0s, row_keys):
            # each row's prompt KV occupies slots [0, S); frontiers are
            # per-row so nothing else moves. zip pairs each state plane
            # (payload or scale) with its [L, n, ...] block — same update
            # either way. The loop is static (n is compile-time).
            for i in range(n):
                blk = tuple(
                    jax.lax.dynamic_slice(
                        r, (0, i) + (0,) * (r.ndim - 2),
                        (r.shape[0], 1) + r.shape[2:],
                    )
                    for r in row_cache
                )
                cache = tuple(
                    jax.lax.dynamic_update_slice(
                        c, b, (0, rows[i]) + (0,) * (c.ndim - 2)
                    )
                    for c, b in zip(cache, blk)
                )
                kv_start = kv_start.at[rows[i]].set(row_starts[i])
                kv_len = kv_len.at[rows[i]].set(S)
                last_tok = last_tok.at[rows[i]].set(tok0s[i])
                active = active.at[rows[i]].set(True)
                rng_keys = rng_keys.at[rows[i]].set(row_keys[i])
            return cache, kv_start, kv_len, last_tok, active, rng_keys

        i32 = jnp.int32
        rep = self.mesh.replicated if self.mesh is not None else None
        out_shardings = (
            (self._cache_shardings(), rep, rep, rep, rep, rep)
            if self.mesh is not None else None
        )
        # row_cache is not donated: an [L,n,...] block cannot alias into the
        # [L,B,...] cache, so donation would only emit a warning
        return jax.jit(insert, donate_argnums=(0, 2, 3, 6), out_shardings=out_shardings).lower(
            self._cache_avals(self.B, self.T),
            self._cache_avals(n, S),
            jax.ShapeDtypeStruct((self.B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((self.B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((self.B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((self.B,), bool, sharding=rep),
            jax.ShapeDtypeStruct((self.B, 2), jnp.uint32, sharding=rep),
            jax.ShapeDtypeStruct((n,), i32, sharding=rep),
            jax.ShapeDtypeStruct((n,), i32, sharding=rep),
            jax.ShapeDtypeStruct((n,), i32, sharding=rep),
            jax.ShapeDtypeStruct((n, 2), jnp.uint32, sharding=rep),
        ).compile()

    def _build_step(self, k: int = 1):
        """The decode executable: ``k`` decode steps for all ``B`` slots as
        ONE device program, returning the ``[k, B]`` token/EOS planes in a
        single host fetch. ``k == 1`` is the classic per-step sync; ``k > 1``
        (``EngineConfig.decode_sync_steps``) scans the step body on device —
        on-device EOS masking makes the blind multi-step correct (a finished
        row stops attending/advancing mid-window), the host just discards
        anything a row produced after its EOS or budget."""
        cfg, dt, sampling = self.config, self.dtypes, self.sampling
        model = self.model_step
        eos_ids = cfg.eos_token_ids
        B, T = self.B, self.T
        kv_quant = self.kv_quant
        from rag_llm_k8s_tpu.models.llama import KVCache

        def one(params, cache_t, kv_start, kv_len, last_tok, active, rng_keys):
            wi = jnp.where(active, kv_len, 0)  # inactive rows park at slot 0
            posv = jnp.clip(wi - kv_start, 0)  # inactive rows: junk, masked
            logits, cache = model.apply(
                {"params": params}, last_tok[:, None], posv[:, None],
                KVCache(*cache_t), kv_start, wi + 1, wi,
            )
            # key = fold(row seed key, token position): draws depend only on
            # the request's own seed + position, never on batchmates — a
            # seeded request samples identically solo or mid-batch
            keys = jax.vmap(jax.random.fold_in)(rng_keys, posv + 1)
            tok = sample_token_per_row(keys, logits[:, 0], sampling)
            hit_eos = _isin(tok, eos_ids)
            # frontier advances only for rows that were active this step and
            # stays < T (the scheduler retires rows before they get close)
            kv_len = jnp.where(active, jnp.minimum(wi + 1, T - 1), kv_len)
            active = active & ~hit_eos
            out = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kv_quant == "int8" else (cache.k, cache.v)
            )
            return out, kv_len, tok, hit_eos, active

        def step(params, cache_t, kv_start, kv_len, last_tok, active, rng_keys):
            if k == 1:
                cache_t, kv_len, tok, hit_eos, active = one(
                    params, cache_t, kv_start, kv_len, last_tok, active, rng_keys
                )
                return cache_t, kv_len, tok, tok[None], hit_eos[None], active

            def body(carry, _):
                cache_t, kv_len, last_tok, active = carry
                cache_t, kv_len, tok, hit_eos, active = one(
                    params, cache_t, kv_start, kv_len, last_tok, active, rng_keys
                )
                return (cache_t, kv_len, tok, active), (tok, hit_eos)

            (cache_t, kv_len, tok, active), (toks, eoss) = jax.lax.scan(
                body, (cache_t, kv_len, last_tok, active), None, length=k
            )
            return cache_t, kv_len, tok, toks, eoss, active

        i32 = jnp.int32
        rep = self.mesh.replicated if self.mesh is not None else None
        out_shardings = (
            (self._cache_shardings(), rep, rep, rep, rep, rep)
            if self.mesh is not None else None
        )
        # kv_start (2) and rng_keys (6) are NOT donated: neither is among the
        # outputs, and the host keeps using their buffers across steps
        return jax.jit(step, donate_argnums=(1, 3, 4, 5), out_shardings=out_shardings).lower(
            param_avals(self.params),
            self._cache_avals(B, T),
            jax.ShapeDtypeStruct((B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), bool, sharding=rep),
            jax.ShapeDtypeStruct((B, 2), jnp.uint32, sharding=rep),
        ).compile()


    # ------------------------------------------------------------------
    # paged executables (EngineConfig.kv_paged)
    # ------------------------------------------------------------------
    def _arena_avals(self):
        """ShapeDtypeStructs for the arena plane tuple (head-sharded over
        tp on a mesh — the same ``_shardings`` specs as the dense cache,
        since kv heads sit at dim 2 in both layouts)."""
        L, K, hd = self.config.num_layers, self.config.num_kv_heads, self.config.head_dim
        N, bs = self.kv_pool.num_blocks, self.block_size
        cdt = jnp.int8 if self.kv_quant == "int8" else self.dtypes.compute_dtype
        pay_sh, sc_sh, _ = self._shardings()
        payload = jax.ShapeDtypeStruct((L, N, K, bs, hd), cdt, sharding=pay_sh)
        if self.kv_quant == "int8":
            scale = jax.ShapeDtypeStruct((L, N, K, bs), jnp.float32, sharding=sc_sh)
            return (payload, payload, scale, scale)
        return (payload, payload)

    def _build_prefill_paged(self, S: int, n: int = 1):
        """Paged admission prefill: ``n`` RIGHT-padded prompts (logical
        positions start at 0 — the layout that makes prefix blocks shareable
        and pad cost zero) prefill into a fresh dense ``[n, S]`` build cache;
        the insert executable scatters the rows into pool blocks. Per-row
        real lengths ride as a vector: the first token samples at each row's
        OWN last real position (vector ``logit_index``), so mixed-length
        admission groups still share one executable."""
        cfg, dt, sampling = self.config, self.dtypes, self.sampling
        model = self.model
        kv_quant = self.kv_quant
        i32 = jnp.int32

        def prefill(params, tokens, lens, rngs):
            cache = make_kv_cache(cfg, n, S, dt.compute_dtype, quant=kv_quant)
            positions = jnp.broadcast_to(jnp.arange(S, dtype=i32)[None, :], (n, S))
            logits, cache = model.apply(
                {"params": params}, tokens, positions, cache,
                jnp.zeros((n,), i32), lens.astype(i32), jnp.int32(0),
                logit_index=jnp.maximum(lens.astype(i32) - 1, 0),
            )
            tok0 = sample_token_per_row(rngs, logits[:, -1], sampling)
            rows = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kv_quant == "int8" else (cache.k, cache.v)
            )
            return rows, tok0

        rep = self.mesh.replicated if self.mesh is not None else None
        # pin output shardings so the row block arrives EXACTLY as
        # insert_paged's lowered avals expect it (same contract as the
        # dense prefill → insert pair)
        out_shardings = (
            (self._cache_shardings(), rep) if self.mesh is not None else None
        )
        return jax.jit(prefill, out_shardings=out_shardings).lower(
            param_avals(self.params),
            jax.ShapeDtypeStruct((n, S), jnp.int32, sharding=rep),
            jax.ShapeDtypeStruct((n,), jnp.int32, sharding=rep),
            jax.ShapeDtypeStruct((n, 2), jnp.uint32, sharding=rep),
        ).compile()

    def _build_insert_paged(self, S: int, n: int = 1):
        """Scatter ``n`` freshly prefilled rows into their pool blocks (ONE
        device call for the group) + splice per-row state. The block loop is
        static (``S // block_size`` slabs per row); slabs whose logical block
        a short prompt never reached carry id 0 — their junk lands in the
        reserved null block, which nothing ever reads, so lazy allocation
        costs no executable shapes."""
        bs = self.block_size
        nb = S // bs

        def insert(arena, row_cache, kv_len, last_tok, active, rng_keys,
                   rows, block_ids, lens, tok0s, row_keys):
            # ONE scatter per plane over the block axis: reshape each row's
            # S-length planes into n*nb slabs and write them at their
            # table-assigned physical ids. An unrolled dynamic_update_slice
            # loop here multiplied the executable's HLO by S/bs (up to
            # hundreds of ops per plane) and with it the warmup compile
            # time; a scatter is fine on this PER-ADMISSION path (the
            # no-scatter rule protects the per-STEP write only). Slabs of
            # never-reached blocks carry id 0 — duplicate null-block
            # indices race, and the null block's content is don't-care.
            flat_ids = block_ids.reshape(-1)  # [n * nb]
            new = []
            for a, r in zip(arena, row_cache):
                L, K = r.shape[0], r.shape[2]
                if a.ndim == 5:
                    hd = r.shape[4]
                    slabs = r.reshape(L, n, K, nb, bs, hd).transpose(
                        0, 1, 3, 2, 4, 5
                    ).reshape(L, n * nb, K, bs, hd)
                else:
                    slabs = r.reshape(L, n, K, nb, bs).transpose(
                        0, 1, 3, 2, 4
                    ).reshape(L, n * nb, K, bs)
                new.append(a.at[:, flat_ids].set(slabs.astype(a.dtype)))
            for i in range(n):
                kv_len = kv_len.at[rows[i]].set(lens[i])
                last_tok = last_tok.at[rows[i]].set(tok0s[i])
                active = active.at[rows[i]].set(True)
                rng_keys = rng_keys.at[rows[i]].set(row_keys[i])
            return tuple(new), kv_len, last_tok, active, rng_keys

        i32 = jnp.int32
        rep = self.mesh.replicated if self.mesh is not None else None
        row_avals = self._cache_avals(n, S)
        out_shardings = (
            (self._arena_shardings(), rep, rep, rep, rep)
            if self.mesh is not None else None
        )
        return jax.jit(
            insert, donate_argnums=(0, 2, 3, 5), out_shardings=out_shardings
        ).lower(
            self._arena_avals(),
            row_avals,
            jax.ShapeDtypeStruct((self.B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((self.B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((self.B,), bool, sharding=rep),
            jax.ShapeDtypeStruct((self.B, 2), jnp.uint32, sharding=rep),
            jax.ShapeDtypeStruct((n,), i32, sharding=rep),
            jax.ShapeDtypeStruct((n, nb), i32, sharding=rep),
            jax.ShapeDtypeStruct((n,), i32, sharding=rep),
            jax.ShapeDtypeStruct((n,), i32, sharding=rep),
            jax.ShapeDtypeStruct((n, 2), jnp.uint32, sharding=rep),
        ).compile()

    def _build_step_paged(self, k: int = 1):
        """The paged decode executable: identical control flow to
        ``_build_step`` — the model streams each row's LIVE blocks via its
        table instead of a dense ``T`` window, so step bandwidth scales with
        real tokens. Tables are NOT donated (host-maintained; one device
        copy serves many windows)."""
        cfg, dt, sampling = self.config, self.dtypes, self.sampling
        model = self.model_step_paged
        eos_ids = cfg.eos_token_ids
        B = self.B
        Tmax = self.MB * self.block_size
        kv_quant = self.kv_quant
        from rag_llm_k8s_tpu.models.llama import KVCache

        def one(params, cache_t, tables, kv_len, last_tok, active, rng_keys):
            wi = jnp.where(active, kv_len, 0)  # inactive rows park at 0
            # an inactive row's junk write must land in the NULL block, not
            # table[row, 0]: a row that hit EOS mid-window still has its
            # real table mapped (the host nulls it only at drain, after the
            # window), and logical block 0 can be a REF-SHARED prefix block
            # — writing there would corrupt every sharer's KV silently
            tables_eff = jnp.where(active[:, None], tables, NULL_BLOCK)
            logits, cache = model.apply(
                {"params": params}, last_tok[:, None], wi[:, None],
                KVCache(*cache_t), jnp.zeros((B,), jnp.int32), wi + 1, wi,
                block_tables=tables_eff,
            )
            # same (seed, position) key fold as the dense step — a request
            # samples identically under either cache layout
            keys = jax.vmap(jax.random.fold_in)(rng_keys, wi + 1)
            tok = sample_token_per_row(keys, logits[:, 0], sampling)
            hit_eos = _isin(tok, eos_ids)
            kv_len = jnp.where(active, jnp.minimum(wi + 1, Tmax - 1), kv_len)
            active = active & ~hit_eos
            out = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kv_quant == "int8" else (cache.k, cache.v)
            )
            return out, kv_len, tok, hit_eos, active

        def step(params, cache_t, tables, kv_len, last_tok, active, rng_keys):
            if k == 1:
                cache_t, kv_len, tok, hit_eos, active = one(
                    params, cache_t, tables, kv_len, last_tok, active, rng_keys
                )
                return cache_t, kv_len, tok, tok[None], hit_eos[None], active

            def body(carry, _):
                cache_t, kv_len, last_tok, active = carry
                cache_t, kv_len, tok, hit_eos, active = one(
                    params, cache_t, tables, kv_len, last_tok, active, rng_keys
                )
                return (cache_t, kv_len, tok, active), (tok, hit_eos)

            (cache_t, kv_len, tok, active), (toks, eoss) = jax.lax.scan(
                body, (cache_t, kv_len, last_tok, active), None, length=k
            )
            return cache_t, kv_len, tok, toks, eoss, active

        i32 = jnp.int32
        rep = self.mesh.replicated if self.mesh is not None else None
        out_shardings = (
            (self._arena_shardings(), rep, rep, rep, rep, rep)
            if self.mesh is not None else None
        )
        return jax.jit(
            step, donate_argnums=(1, 3, 4, 5), out_shardings=out_shardings
        ).lower(
            param_avals(self.params),
            self._arena_avals(),
            jax.ShapeDtypeStruct((B, self.MB), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), bool, sharding=rep),
            jax.ShapeDtypeStruct((B, 2), jnp.uint32, sharding=rep),
        ).compile()

    def _build_verify_paged(self, K: int):
        """The speculative VERIFY executable (ISSUE 13): one device call
        feeds every active row ``last_tok`` + its ``K`` drafted tokens
        through the paged chunked model — the masked-plane scatter writes
        all ``K+1`` positions through each row's block table (per-row
        vector base + lane offsets, the same write the admission chunk
        path uses), the paged chunk kernel attends each lane with offset
        causality, and ``K+1`` logit planes come back instead of one.

        Acceptance happens ON DEVICE so the host fetch stays one
        round-trip: plane ``j``'s TARGET is what the vanilla step loop
        would have sampled at that position — argmax for greedy, the
        (seed, position)-keyed categorical draw for sampling (the fold
        sequence continues exactly, so seeded streams match bit-for-bit;
        engine/sampling.py). A row accepts the longest draft prefix equal
        to its targets and emits the target at the first mismatch (the
        correction) or the bonus target on full acceptance — the emitted
        stream is the vanilla stream BY CONSTRUCTION, speculation only
        changes how many tokens one window retires.

        Rejected lanes need no explicit retraction: their KV writes land
        beyond the advanced ``kv_len`` frontier, where no kernel window
        ever reads and the next window overwrites — the same masking
        discipline that makes blind multi-step sync windows correct.
        Lanes past a row's own ``n_drafts`` (rows draft different lengths
        in one window) write junk into mapped-but-beyond-frontier slots
        or, past the row's table, the NULL block (the llama.py scatter
        parks out-of-table positions there). Inactive rows park wholesale
        at the null block, exactly like the plain step."""
        cfg, dt, sampling = self.config, self.dtypes, self.sampling
        model = self.model_chunked_paged
        eos_ids = cfg.eos_token_ids
        B = self.B
        S = K + 1
        Tmax = self.MB * self.block_size
        kv_quant = self.kv_quant
        i32 = jnp.int32
        from rag_llm_k8s_tpu.models.llama import KVCache

        def verify(params, cache_t, tables, kv_len, last_tok, active,
                   rng_keys, drafts, n_drafts):
            wi = jnp.where(active, kv_len, 0)  # inactive rows park at 0
            # inactive rows' junk routes to the NULL block (same rule as
            # the plain step: an EOS'd row's table is still mapped until
            # the host drains, and logical block 0 can be ref-shared)
            tables_eff = jnp.where(active[:, None], tables, NULL_BLOCK)
            nd = jnp.where(active, n_drafts, 0)
            fed = jnp.concatenate([last_tok[:, None], drafts], axis=1)
            pos = wi[:, None] + jnp.arange(S, dtype=i32)[None, :]  # [B, S]
            # the deepest VALID lane (j = nd) attends keys <= wi + nd:
            # kv_len = wi + nd + 1 caps every row's window there; junk
            # lanes beyond see a truncated window and junk logits nobody
            # samples from
            logits, cache = model.apply(
                {"params": params}, fed, pos, KVCache(*cache_t),
                jnp.zeros((B,), i32), wi + 1 + nd, wi,
                block_tables=tables_eff,
            )
            # plane j samples the token that will sit at position
            # wi + j + 1 — fold EXACTLY the key the vanilla step would
            # have folded for it ((seed, position) discipline)
            keys = jax.vmap(
                jax.vmap(jax.random.fold_in, in_axes=(None, 0))
            )(rng_keys, pos + 1)  # [B, S, 2]
            targets = sample_targets_per_row(keys, logits, sampling)
            m, emitted = accept_drafts(drafts, targets, nd)
            jj = jnp.arange(S, dtype=i32)[None, :]
            is_eos = _isin(emitted, eos_ids)  # [B, S] elementwise
            hit_eos = jnp.any(is_eos & (jj <= m[:, None]), axis=1)
            # frontier: last_tok's KV at wi + accepted drafts' at
            # wi+1..wi+m are valid; the correction token (plane m) is the
            # new last_tok, written next window at the new frontier —
            # identical bookkeeping to m+1 vanilla steps
            kv_len = jnp.where(
                active, jnp.minimum(wi + m + 1, Tmax - 1), kv_len
            )
            new_last = jnp.take_along_axis(emitted, m[:, None], axis=1)[:, 0]
            last_tok = jnp.where(active, new_last, last_tok)
            n_emit = jnp.where(active, m + 1, 0)
            active = active & ~hit_eos
            out = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kv_quant == "int8" else (cache.k, cache.v)
            )
            # [S, B] planes mirror the plain step's [k, B] fetch layout
            return (
                out, kv_len, last_tok, emitted.T, n_emit, is_eos.T,
                m, active,
            )

        rep = self.mesh.replicated if self.mesh is not None else None
        out_shardings = (
            (self._arena_shardings(), rep, rep, rep, rep, rep, rep, rep)
            if self.mesh is not None else None
        )
        # tables/rng_keys/drafts are host-fed per window, never donated
        return jax.jit(
            verify, donate_argnums=(1, 3, 4, 5), out_shardings=out_shardings
        ).lower(
            param_avals(self.params),
            self._arena_avals(),
            jax.ShapeDtypeStruct((B, self.MB), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), bool, sharding=rep),
            jax.ShapeDtypeStruct((B, 2), jnp.uint32, sharding=rep),
            jax.ShapeDtypeStruct((B, K), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), i32, sharding=rep),
        ).compile()

    def _build_mixed_step(self, C: int):
        """The MIXED decode+chunk window executable (ISSUE 16): one device
        call advances every active decode lane by one token AND feeds each
        scheduled admission a ``<= C``-token slice of its prompt through
        the paged chunked model — ``paged_chunk_attention``'s third
        consumer, after prefix splicing and speculative verify. Lane
        width ``C`` is static (one compile per chunk size); rows declare
        their role per window with host-fed vectors:

        - decode rows (``active`` & ``n_fed == 0``): lane 0 carries the
          device-resident ``last_tok`` at position ``kv_len`` — exactly
          the plain step's write/attend/sample, with ``C - 1`` junk lanes
          beyond the frontier (verify's masking discipline);
        - chunk rows (``n_fed > 0``): lanes ``0..n_fed-1`` carry prompt
          tokens at canonical positions ``chunk_base + j`` (``chunk_base``
          is HOST-fed — a never-inserted prefilling row's device
          ``kv_len`` is junk), written through the row's block table with
          offset causality. The FINAL chunk additionally samples the
          first token from lane ``n_fed - 1``'s plane;
        - everything else parks wholesale at the NULL block.

        Byte-identity falls out of the (seed, position) key discipline:
        every row folds ``fold_in(row_key, base + n_eff)`` — a decode row
        folds ``wi + 1`` exactly like ``_build_step_paged``, and a final
        chunk folds ``fold_in(row_key, prompt_len)`` exactly like the
        one-shot admission — so the window's shape cannot change any
        draw, and chunked prompt KV bit-equals one-shot prefill KV (same
        canonical positions, same kernel)."""
        cfg, dt, sampling = self.config, self.dtypes, self.sampling
        model = self.model_chunked_paged
        eos_ids = cfg.eos_token_ids
        B = self.B
        Tmax = self.MB * self.block_size
        kv_quant = self.kv_quant
        i32 = jnp.int32
        from rag_llm_k8s_tpu.models.llama import KVCache

        def mixed(params, cache_t, tables, kv_len, last_tok, active,
                  rng_keys, fed, n_fed, chunk_base, final):
            is_chunk = n_fed > 0
            is_dec = active & ~is_chunk
            # tokens each row really feeds this window: 1 for decode
            # lanes, the slice width for chunk rows, 0 for bystanders
            n_eff = jnp.where(is_dec, 1, n_fed)
            part = n_eff > 0
            # decode rows anchor at the device frontier; chunk rows at the
            # host-tracked progress frontier (their device kv_len is junk
            # until the final chunk lands)
            base = jnp.where(
                is_chunk, chunk_base, jnp.where(active, kv_len, 0)
            )
            # decode rows' lane 0 is the device-resident last_tok — the
            # host never fetches it between windows (same reason the
            # plain step keeps it on device)
            lane0 = jnp.arange(C, dtype=i32)[None, :] == 0
            fed_eff = jnp.where(is_dec[:, None] & lane0, last_tok[:, None], fed)
            # bystanders' junk routes to the NULL block (same rule as the
            # plain step: an EOS'd row's table is still mapped until the
            # host drains, and logical block 0 can be ref-shared)
            tables_eff = jnp.where(part[:, None], tables, NULL_BLOCK)
            pos = base[:, None] + jnp.arange(C, dtype=i32)[None, :]  # [B, C]
            # the deepest REAL lane (j = n_eff - 1) attends keys
            # <= base + n_eff - 1: kv_len = base + n_eff caps every row's
            # window there; junk lanes beyond see truncated windows and
            # junk logits nobody samples from
            logits, cache = model.apply(
                {"params": params}, fed_eff, pos, KVCache(*cache_t),
                jnp.zeros((B,), i32), base + n_eff, base,
                block_tables=tables_eff,
            )
            # each row samples from its last REAL lane's plane: plane 0
            # for decode (= the plain step's logits[:, 0]), plane
            # n_fed - 1 for a final chunk (= the one-shot admission's
            # logit_index = prompt_len - 1 plane)
            sel = jnp.take_along_axis(
                logits, jnp.maximum(n_eff - 1, 0)[:, None, None], axis=1
            )[:, 0]
            keys = jax.vmap(jax.random.fold_in)(rng_keys, base + n_eff)
            tok = sample_token_per_row(keys, sel, sampling)
            hit_eos = _isin(tok, eos_ids)
            # frontier: base + n_eff KV positions are now written — wi + 1
            # for decode (the plain step's update), prompt progress for
            # chunk rows (the final chunk lands kv_len = prompt_len, the
            # exact post-admission invariant: tok0's KV writes next window)
            kv_len = jnp.where(
                part, jnp.minimum(base + n_eff, Tmax - 1), kv_len
            )
            last_tok = jnp.where(is_dec | final, tok, last_tok)
            # final chunks activate their row (admission complete); decode
            # rows stay active; both retire on EOS. Mid-prompt chunk rows
            # stay device-inactive until their final chunk.
            active = (active | final) & ~hit_eos
            out = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kv_quant == "int8" else (cache.k, cache.v)
            )
            return out, kv_len, last_tok, tok, hit_eos, active

        rep = self.mesh.replicated if self.mesh is not None else None
        out_shardings = (
            (self._arena_shardings(), rep, rep, rep, rep, rep)
            if self.mesh is not None else None
        )
        # tables/rng_keys/fed/n_fed/chunk_base/final are host-fed per
        # window, never donated
        return jax.jit(
            mixed, donate_argnums=(1, 3, 4, 5), out_shardings=out_shardings
        ).lower(
            param_avals(self.params),
            self._arena_avals(),
            jax.ShapeDtypeStruct((B, self.MB), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), bool, sharding=rep),
            jax.ShapeDtypeStruct((B, 2), jnp.uint32, sharding=rep),
            jax.ShapeDtypeStruct((B, C), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((B,), bool, sharding=rep),
        ).compile()

    def _build_prefix_scatter(self, P: int):
        """Scatter a ``CachedPrefix``'s splice-buffer planes into pool
        blocks: a static loop over the buffer's ``P // block_size`` slabs,
        each landing at its table-assigned physical block (id 0 = the null
        block for slabs past the real prefix — junk nothing reads). Serves
        both the miss path (all blocks private) and the hit path (shared
        blocks carry id 0 here — already populated, skip the write)."""
        bs = self.block_size
        nbp = P // bs  # admit_prefixed validates P % block_size == 0

        def scatter(arena, planes, ids):
            # ONE scatter per plane (same shape discipline as insert_paged:
            # an unrolled loop here emitted P/bs slice/update pairs per
            # plane — hundreds of HLO ops at the 4096-token default buffer)
            new = []
            for a, p in zip(arena, planes):
                L, K = p.shape[0], p.shape[2]
                if a.ndim == 5:
                    hd = p.shape[4]
                    slabs = p[:, 0, :, : nbp * bs].reshape(
                        L, K, nbp, bs, hd
                    ).transpose(0, 2, 1, 3, 4)  # [L, nbp, K, bs, hd]
                else:
                    slabs = p[:, 0, :, : nbp * bs].reshape(
                        L, K, nbp, bs
                    ).transpose(0, 2, 1, 3)
                new.append(a.at[:, ids].set(slabs.astype(a.dtype)))
            return tuple(new)

        i32 = jnp.int32
        rep = self.mesh.replicated if self.mesh is not None else None
        plane_avals = tuple(
            jax.ShapeDtypeStruct(shape, dtype, sharding=rep)
            for shape, dtype in self._prefix_plane_shapes(P)
        )
        out_shardings = (
            self._arena_shardings() if self.mesh is not None else None
        )
        return jax.jit(
            scatter, donate_argnums=(0,), out_shardings=out_shardings
        ).lower(
            self._arena_avals(),
            plane_avals,
            jax.ShapeDtypeStruct((nbp,), i32, sharding=rep),
        ).compile()

    def _build_prefill_px_paged(self, C: int):
        """Paged PREFIXED admission, batch 1: the prefix KV already sits in
        this row's pool blocks (shared copy-free via ref counts, or freshly
        scattered from the descriptor); only the ``C``-bucketed suffix
        prefills, as a paged CHUNK over the row's table (queries at logical
        ``plen + t``, offset causality). Writes go straight into pool
        blocks — no per-row ``(S,)`` cache materialization or splice."""
        cfg, dt, sampling = self.config, self.dtypes, self.sampling
        model = self.model_chunked_paged
        kv_quant = self.kv_quant
        i32 = jnp.int32
        from rag_llm_k8s_tpu.models.llama import KVCache

        def px(params, arena, row_table, suffix_tokens, slen, plen, rngs):
            positions = (plen + jnp.arange(C, dtype=i32))[None, :]
            total = (plen + slen).astype(i32)
            logits, cache = model.apply(
                {"params": params}, suffix_tokens, positions,
                KVCache(*arena), jnp.zeros((1,), i32),
                jnp.broadcast_to(total, (1,)), jnp.broadcast_to(plen, (1,)),
                logit_index=jnp.maximum(slen - 1, 0),
                block_tables=row_table,
            )
            tok0 = sample_token_per_row(rngs, logits[:, -1], sampling)
            out = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kv_quant == "int8" else (cache.k, cache.v)
            )
            return out, tok0

        rep = self.mesh.replicated if self.mesh is not None else None
        out_shardings = (
            (self._arena_shardings(), rep) if self.mesh is not None else None
        )
        return jax.jit(
            px, donate_argnums=(1,), out_shardings=out_shardings
        ).lower(
            param_avals(self.params),
            self._arena_avals(),
            jax.ShapeDtypeStruct((1, self.MB), i32, sharding=rep),
            jax.ShapeDtypeStruct((1, C), jnp.int32, sharding=rep),
            jax.ShapeDtypeStruct((), jnp.int32, sharding=rep),
            jax.ShapeDtypeStruct((), jnp.int32, sharding=rep),
            jax.ShapeDtypeStruct((1, 2), jnp.uint32, sharding=rep),
        ).compile()

    def _build_chunk_splice(self, nb: int):
        """Per-chunk paged splice (chunk-granular prefix reuse): copy ``nb``
        physical blocks' K/V from a chunk's canonical registration into
        freshly allocated destination blocks, position-shifting K by the
        closed-form RoPE ``delta`` rotation in the same pass (the int8
        arena goes dequant → rotate → requant with per-vector scale
        recomputation). V is position-free and copies untouched. One
        executable per block count, like every other admission-path op."""
        from rag_llm_k8s_tpu.models.llama import rope_frequencies
        from rag_llm_k8s_tpu.ops.attention import (
            rope_rerotate,
            rope_rerotate_q8,
        )

        inv = rope_frequencies(self.config)
        kv_quant = self.kv_quant
        i32 = jnp.int32

        def splice(arena, src, dst, delta):
            k, v = arena[0], arena[1]
            ks = jnp.take(k, src, axis=1)  # [L, nb, K, bs, hd]
            vs = jnp.take(v, src, axis=1)
            if kv_quant == "int8":
                ksc = jnp.take(arena[2], src, axis=1)  # [L, nb, K, bs]
                vsc = jnp.take(arena[3], src, axis=1)
                rk, rks = rope_rerotate_q8(ks, ksc, delta, inv)
                return (
                    k.at[:, dst].set(rk),
                    v.at[:, dst].set(vs),
                    arena[2].at[:, dst].set(rks),
                    arena[3].at[:, dst].set(vsc),
                )
            rk = rope_rerotate(ks, delta, inv)
            return (k.at[:, dst].set(rk), v.at[:, dst].set(vs))

        rep = self.mesh.replicated if self.mesh is not None else None
        out_shardings = (
            self._arena_shardings() if self.mesh is not None else None
        )
        return jax.jit(
            splice, donate_argnums=(0,), out_shardings=out_shardings
        ).lower(
            self._arena_avals(),
            jax.ShapeDtypeStruct((nb,), i32, sharding=rep),
            jax.ShapeDtypeStruct((nb,), i32, sharding=rep),
            jax.ShapeDtypeStruct((), i32, sharding=rep),
        ).compile()

    def _packet_avals(self, nb: int):
        """The migration packet's plane avals: the arena tuple with its
        block axis cut to ``nb`` — same dtypes, same shardings (kv heads
        sit at dim 2 either way), so a packet gathered on one engine
        feeds another engine's import executable with no reshard."""
        out = []
        for av in self._arena_avals():
            shape = (av.shape[0], nb) + av.shape[2:]
            out.append(
                jax.ShapeDtypeStruct(shape, av.dtype, sharding=av.sharding)
            )
        return tuple(out)

    def _build_migrate_out(self, nb: int):
        """Gather one migrating row's ``nb`` pool blocks out of the arena
        as a self-contained plane tuple (``[L, nb, K, bs, hd]`` payload +
        int8 scale planes) — the prefill→decode hand-off's device copy.
        NOTHING is donated: a failure here leaves the source engine fully
        intact (the scheduler just keeps decoding the request locally).
        Ids are padded to the admission bucket's block count with the
        NULL block, so the executable ladder stays as bounded as
        admission's. One executable per block count, like chunk_splice."""
        def gather(arena, ids):
            return tuple(jnp.take(a, ids, axis=1) for a in arena)

        rep = self.mesh.replicated if self.mesh is not None else None
        if self.mesh is not None:
            pay_sh, sc_sh, _ = self._shardings()
            out_shardings = tuple(
                pay_sh if len(av.shape) == 5 else sc_sh
                for av in self._arena_avals()
            )
        else:
            out_shardings = None
        return jax.jit(gather, out_shardings=out_shardings).lower(
            self._arena_avals(),
            jax.ShapeDtypeStruct((nb,), jnp.int32, sharding=rep),
        ).compile()

    def _build_migrate_in(self, nb: int):
        """Scatter a migrated packet's planes into freshly allocated
        destination blocks + splice the row's sampling state (kv_len,
        last_tok, active, UNFOLDED rng key) in the same device call —
        the decode-role twin of ``insert_paged`` for a row whose KV was
        computed elsewhere. The copy is bit-exact (same dtype both
        sides) and the state triple reproduces the source row, so the
        next decode step folds ``(row_key, kv_len + 1)`` exactly as a
        unified run would: streams are byte-identical by construction.
        Padded slabs carry the NULL block id — their junk lands in the
        reserved null block, the same don't-care discipline as insert."""
        i32 = jnp.int32

        def splice(arena, planes, kv_len, last_tok, active, rng_keys,
                   row, dst, length, tok, row_key):
            new = tuple(
                a.at[:, dst].set(p.astype(a.dtype))
                for a, p in zip(arena, planes)
            )
            kv_len = kv_len.at[row].set(length)
            last_tok = last_tok.at[row].set(tok)
            active = active.at[row].set(True)
            rng_keys = rng_keys.at[row].set(row_key)
            return new, kv_len, last_tok, active, rng_keys

        rep = self.mesh.replicated if self.mesh is not None else None
        out_shardings = (
            (self._arena_shardings(), rep, rep, rep, rep)
            if self.mesh is not None else None
        )
        return jax.jit(
            splice, donate_argnums=(0, 2, 3, 5), out_shardings=out_shardings
        ).lower(
            self._arena_avals(),
            self._packet_avals(nb),
            jax.ShapeDtypeStruct((self.B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((self.B,), i32, sharding=rep),
            jax.ShapeDtypeStruct((self.B,), bool, sharding=rep),
            jax.ShapeDtypeStruct((self.B, 2), jnp.uint32, sharding=rep),
            jax.ShapeDtypeStruct((), i32, sharding=rep),
            jax.ShapeDtypeStruct((nb,), i32, sharding=rep),
            jax.ShapeDtypeStruct((), i32, sharding=rep),
            jax.ShapeDtypeStruct((), i32, sharding=rep),
            jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=rep),
        ).compile()

    def _build_boundary_px_paged(self, W: int):
        """Boundary-correction re-prefill straight into pool blocks: the
        first ``W`` tokens of a spliced chunk recompute THROUGH the model
        with the true left context (offset causality over the row's table
        at logical ``woff``; ``kv_len = woff + W`` hides everything to the
        right), their fresh K/V scattering into the already-mapped
        destination blocks. No sampling, no logits consumed — exactly the
        width is written, so the spliced tail beyond the window survives
        (unlike the right-padded suffix prefill, whose pad writes land
        outside every kv window)."""
        model = self.model_chunked_paged
        kv_quant = self.kv_quant
        i32 = jnp.int32
        from rag_llm_k8s_tpu.models.llama import KVCache

        def bfix(params, arena, row_table, toks, woff):
            positions = (woff + jnp.arange(W, dtype=i32))[None, :]
            kv_len = jnp.broadcast_to(woff + W, (1,)).astype(i32)
            _, cache = model.apply(
                {"params": params}, toks, positions, KVCache(*arena),
                jnp.zeros((1,), i32), kv_len,
                jnp.broadcast_to(woff, (1,)),
                logit_index=jnp.int32(0), block_tables=row_table,
            )
            return (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if kv_quant == "int8" else (cache.k, cache.v)
            )

        rep = self.mesh.replicated if self.mesh is not None else None
        out_shardings = (
            self._arena_shardings() if self.mesh is not None else None
        )
        return jax.jit(
            bfix, donate_argnums=(1,), out_shardings=out_shardings
        ).lower(
            param_avals(self.params),
            self._arena_avals(),
            jax.ShapeDtypeStruct((1, self.MB), i32, sharding=rep),
            jax.ShapeDtypeStruct((1, W), jnp.int32, sharding=rep),
            jax.ShapeDtypeStruct((), jnp.int32, sharding=rep),
        ).compile()

    # ------------------------------------------------------------------
    # chunk-granular registrations (reuse="chunk"; scheduler thread only)
    # ------------------------------------------------------------------
    def _chunk_splice_plan(self, prefix):
        """Can this prefix assemble from per-chunk canonical registrations?
        Returns ``[(span, registration), ...]`` covering the WHOLE prefix —
        every span block-aligned, every registration stamp-matched to the
        cache entry the span was resolved from — or None (the admission
        falls back to the buffer-scatter path). All-or-nothing: a partial
        assembly would still scatter the rest, paying both paths."""
        chunks = getattr(prefix, "chunks", None)
        if not chunks or not self._chunk_regs:
            return None
        bs = self.block_size
        if sum(c.length for c in chunks) != int(prefix.length):
            return None
        plan = []
        for c in chunks:
            if c.off % bs or c.length % bs or c.length == 0:
                return None
            reg = self._chunk_regs.get(c.key)
            if (
                reg is None or reg[3] != c.stamp or reg[2] != c.length
                or reg[1] % bs or len(reg[0]) != c.length // bs
            ):
                return None
            plan.append((c, reg))
        try:
            # fault site "chunk_splice": a mid-splice fault pool-side.
            # Nothing is allocated yet — decline the plan and the admission
            # recomputes via the buffer-scatter path, leaking zero blocks.
            faults.maybe_fail("chunk_splice")
        except faults.InjectedFault:
            return None
        for c, _ in plan:
            # planned = in use: the cap evicts least-recently-PLANNED
            self._chunk_regs.move_to_end(c.key)
        return plan

    def _chunk_splice_into_row(self, row: int, ids_all: List[int], plan):
        """Assemble a row's prefix from per-chunk canonical registrations:
        gather each span's source blocks, re-rotate K by the span's
        position delta into the row's destination blocks, then run the
        bounded boundary-correction prefills in ascending offset order
        (each sees the corrected chunks to its left; ``kv_len`` caps its
        view below everything to the right). Device work only — the caller
        owns alloc/assign and the EngineStateLost contract."""
        bs = self.block_size
        for c, reg in plan:
            src_ids, canon_off = reg[0], reg[1]
            nb = len(src_ids)
            dst = ids_all[c.off // bs : c.off // bs + nb]
            delta = c.off - canon_off
            self._cache = self._get("chunk_splice", nb)(
                self._cache,
                self._put(jnp.asarray(np.asarray(src_ids, np.int32))),
                self._put(jnp.asarray(np.asarray(dst, np.int32))),
                self._put(jnp.int32(delta)),
            )
            if delta:
                flight.emit("rerotate", tokens=c.length, delta=delta)
            flight.emit("chunk_splice", tokens=c.length, delta=delta, pool=1)
        row_table = None
        for c, reg in plan:
            delta = c.off - reg[1]
            if (c.exact and delta == 0) or not c.fixup_ids:
                continue  # canonical placement: content already faithful
            W = len(c.fixup_ids)
            if row_table is None:
                row_table = self._put(
                    jnp.asarray(self._tables_host[row : row + 1])
                )
            toks = np.asarray([list(c.fixup_ids)], np.int32)
            self._cache = self._get("boundary_px", W)(
                self.params, self._cache, row_table,
                self._put(jnp.asarray(toks)), self._put(jnp.int32(c.off)),
            )
            flight.emit("boundary_fixup", tokens=W)

    def _register_chunks_from_scatter(self, prefix, ids_all: List[int],
                                      chain_registered: bool = False):
        """After a buffer-scatter admission, register each block-aligned
        EXACT span's freshly scattered blocks as the chunk's canonical pool
        copy (one pool ref each — they outlive the row). Only exact spans
        qualify: registering a re-rotated copy would compound drift when a
        later splice rotates it again. Stamp identity ties the
        registration to the prefix-cache entry, so a rebuilt entry's stale
        registration simply stops matching. Only call this from the
        admission that actually SCATTERED the blocks — on a chain hit the
        block content was written by an earlier admission and this
        resolve's spans do not describe it. ``chain_registered``: this
        admission's full blocks are ALSO chain-registered — the chunk
        registrations then carry ``counted=False``, which gates ALL THREE
        accountings (fragmentation tokens, the reclaimable-blocks hint,
        and the pool's warm-tier ledger): a chain-covered chunk reg's
        drop frees no blocks while the chain ref lives, so advertising it
        reclaimable would make the gate queue a request no sweep can
        place (gauge-grade: once the chain registration drops, its chunk
        regs under-report until they too are swept)."""
        chunks = getattr(prefix, "chunks", None)
        if not chunks:
            return
        bs = self.block_size
        pc = getattr(self.engine_config, "prefix_cache", None)
        cap = max(1, int(getattr(pc, "chunk_pool_regs", 32) or 32))
        full_tokens = (int(prefix.length) // bs) * bs
        for c in chunks:
            if (
                not c.exact or c.length == 0
                or c.off % bs or c.length % bs
                or c.off + c.length > full_tokens
            ):
                continue
            old = self._chunk_regs.get(c.key)
            if old is not None and old[3] == c.stamp:
                continue  # this entry generation is already registered
            nb = c.length // bs
            span_ids = ids_all[c.off // bs : c.off // bs + nb]
            self.kv_pool.ref(span_ids)  # the registration's own ref
            if old is not None:
                self._drop_chunk_reg(c.key)
            counted = not chain_registered
            self._chunk_regs[c.key] = (
                list(span_ids), c.off, c.length, c.stamp, counted
            )
            if counted:
                self._chunk_reg_tokens += c.length
                self._reclaimable_blocks += len(span_ids)
                self.kv_pool.account_tier("warm", len(span_ids))
            while len(self._chunk_regs) > cap:  # bounded registration set
                self._drop_chunk_reg(next(iter(self._chunk_regs)))

    def _drop_chunk_reg(self, key) -> bool:
        """The one place a chunk registration dies: pops the entry, fixes
        the fragmentation counter, returns the blocks to the pool."""
        reg = self._chunk_regs.pop(key, None)
        if reg is None:
            return False
        if reg[4]:
            n = len(reg[0])
            self._chunk_reg_tokens -= reg[2]
            self._reclaimable_blocks = max(0, self._reclaimable_blocks - n)
            self.kv_pool.account_tier("warm", -n)
        self.kv_pool.free(reg[0])
        return True

    # ------------------------------------------------------------------
    # paged host bookkeeping (scheduler thread only, like the operations)
    # ------------------------------------------------------------------
    def _device_tables(self):
        """The device copy of the block tables, refreshed only when the host
        tables changed (admission, growth, retire) — a [B, MB] int32 put,
        tiny next to any step."""
        if self._tables_dirty or self._tables_dev is None:
            self._tables_dev = self._put(jnp.asarray(self._tables_host))
            self._tables_dirty = False
        return self._tables_dev

    def _assign_row_blocks(self, row: int, ids: List[int], start_block: int = 0):
        """Map ``ids`` into the row's table at logical blocks
        ``[start_block, ...)`` and record ownership."""
        for j, b in enumerate(ids):
            self._tables_host[row, start_block + j] = b
        self._slot_blocks[row].extend(ids)
        self._tables_dirty = True

    def _release_row(self, row: int) -> None:
        """Return the row's blocks to the pool and null its table — MUST
        happen before the next step: an inactive row still writes its junk
        token at table[row, 0], and a stale entry would corrupt whoever the
        freed block is reallocated to."""
        if self._slot_blocks[row]:
            self.kv_pool.free(self._slot_blocks[row])
            self._slot_blocks[row] = []
        if self._tables_host[row].any():
            self._tables_host[row, :] = NULL_BLOCK
            self._tables_dirty = True

    def _retire_rows(self, rows: List[int]) -> None:
        """Paged-mode retire hook (budget/EOS/evict): record the per-request
        block footprint, then free."""
        if not self.paged:
            return
        if len(self._blocks_at_retire) > 8192:
            # raw-engine callers (tests, benches) never pop; don't let the
            # footprint map grow without bound under them
            self._blocks_at_retire.clear()
        for r in rows:
            rid = self.slots[r].request_id
            if rid >= 0:
                self._blocks_at_retire[rid] = len(self._slot_blocks[r])
            self._release_row(r)

    def pop_blocks_allocated(self, request_id: int) -> Optional[int]:
        """Blocks the request held at retirement (paged; None otherwise) —
        the scheduler forwards it into the response timings."""
        if not self.paged:
            return None
        return self._blocks_at_retire.pop(request_id, None)

    # ------------------------------------------------------------------
    # goodput ledger plumbing (obs/goodput.py; scheduler thread only)
    # ------------------------------------------------------------------
    def mark_rework(self, request_id: int) -> None:
        """The next admission of ``request_id`` re-feeds tokens already
        computed once (preemption resume / reset resubmission): its real
        token lanes attribute to ``preempt_rework``, not fresh prefill.
        The mark is consumed by exactly one admission — rework is never
        double-counted."""
        if len(self._rework_rids) > 4096:  # stale marks of failed retries
            # sweep BEFORE adding: the fresh mark (and only accreted stale
            # ones) must survive the overflow, or the very resubmission
            # that tripped it loses its rework attribution
            self._rework_rids.clear()
        self._rework_rids.add(request_id)

    def _take_rework(self, rids) -> "set":
        taken = {r for r in rids if r in self._rework_rids}
        self._rework_rids -= taken
        return taken

    def pop_request_goodput(self, request_id: int,
                            tokens: float = 0.0) -> Optional[Dict]:
        """One completed request's attributed chip-time figures (chip_ms,
        goodput_frac, cost_usd, speculation stats) — the scheduler
        forwards them into the response timings at delivery. ``tokens``
        (the delivered count) feeds the ledger's per-tenant rollup."""
        return self.ledger.pop_request(request_id, tokens=tokens)

    def pop_spec_seen(self, request_id: int) -> bool:
        """True iff any verify window ever judged drafts for this request
        — the spec_verify half of the per-request approximation
        fingerprint (obs/shadow.py), independent of the goodput ledger.
        Popping keeps the set bounded by in-flight requests."""
        try:
            self._spec_rids.remove(request_id)
            return True
        except KeyError:
            return False

    def discard_request_goodput(self, request_id: int) -> None:
        """Reclaim a never-delivered request's ledger entry (gave up /
        deadline eviction / shutdown) — without this, failed requests
        accrete until the bounded map evicts in-flight entries with them.
        The spec-fingerprint set shares the cleanup (same lifetime)."""
        self.ledger.discard_request(request_id)
        self._spec_rids.discard(request_id)

    def _journal_window(self, summary) -> None:
        if summary is not None:
            flight.emit("goodput_window", **summary)

    def _journal_emitted(self) -> None:
        """Flight-WAL watermark pass (every sync-window drain): journal
        each live row's emitted-token delta as one ``token_emit`` event,
        so concatenating a request's token_emit events rebuilds its full
        emitted stream — the state a warm restart folds back in. Gated on
        an attached WAL: without one this is a no-op (the ring needs no
        per-window token copies; greedy resume recomputes). Tokens
        appended after the last window before a SIGKILL are simply
        recomputed on resume — deterministic decode makes the tail safe
        to lose."""
        if not flight.wal_enabled():
            return
        for slot in self.slots:
            if slot.active and len(slot.tokens) > slot.wal_mark:
                flight.emit("token_emit", slot.request_id,
                            toks=slot.tokens[slot.wal_mark:])
                slot.wal_mark = len(slot.tokens)

    def blocks_needed(self, prompt_len: int) -> int:
        """Admission-time block cost of a prompt (0 in dense mode)."""
        if not self.paged:
            return 0
        return self.kv_pool.blocks_for(max(int(prompt_len), 1))

    def admission_state(self, prompt_len: int) -> str:
        """'ok' — admissible now; 'wait' — pool pressure, decode will free
        blocks; 'never' — the prompt alone outsizes the whole pool."""
        if not self.paged:
            return "ok"
        # the verdict arithmetic (never / incremental-ok / +1-headroom
        # want) is the decision core's; only the stateful reclaim loop
        # below stays here
        verdict, want = sim_policy.admission_verdict(
            self.blocks_needed(prompt_len), self.kv_pool.usable_blocks(),
            self.interleave_on, self.MB,
        )
        if verdict != "check":
            return verdict
        if self.kv_pool.can_alloc(want):
            return "ok"
        if self._prefix_blocks or self._chunk_regs:
            # tier occupancy, not raw headroom: WARM registrations give
            # their blocks to a live admission even while rows decode —
            # the chunk KV survives (int8) in the prefix cache, one
            # re-scatter away, so reclaiming them costs a future re-stage,
            # never a re-prefill. HOT registrations are proven-shared
            # working set and are only sacrificed when nothing decodes
            # (the idle branch below).
            if self._chunk_regs:
                # chunk-canonical copies go FIRST (same order as the
                # growth-pressure path): pure prefill avoidance, rebuilt
                # from the prefix cache on the next exact scatter —
                # cheaper to restore than a whole warm chain's re-stage
                for key in list(self._chunk_regs):
                    self._drop_chunk_reg(key)
                    if self.kv_pool.can_alloc(want):
                        return "ok"
            for key in [
                k for k, t in list(self._prefix_tier.items()) if t != "hot"
            ]:
                self._drop_registration(key)
                if self.kv_pool.can_alloc(want):
                    return "ok"
        if self._prefix_blocks and not self.has_active():
            # nothing is decoding, yet the pool can't take one prompt: the
            # registered prefix blocks are the only other holder — drop the
            # oldest registrations until the admission fits (cache refs are
            # re-buildable; a wedged queue is not)
            for key in list(self._prefix_blocks):
                self._drop_registration(key)
                if self.kv_pool.can_alloc(want):
                    return "ok"
        return "wait" if self.has_active() else (
            "ok" if self.kv_pool.can_alloc(want) else "never"
        )

    def _ensure_decode_blocks(
        self, horizon: "Optional[Dict[int, int]]" = None
    ) -> None:
        """Grow every active row's table to cover the next sync window
        (positions up to ``kv_ub + k``) BEFORE the device call — a write
        landing in an unmapped block would vanish into the null block and
        corrupt the stream one step later. ``horizon`` overrides the
        per-row token horizon (speculative verify windows write
        ``n_drafts + 1`` positions per row, not ``sync_steps`` — rows
        draft different lengths, so the map is per-row). Exhaustion
        preempts the NEWEST-admitted rows (their emitted tokens return to
        the scheduler, which resubmits once blocks free — vLLM-style
        recompute preemption) until the remaining rows fit."""
        k = self.sync_steps
        while True:
            # mapped logical blocks are contiguous from 0, so the
            # ownership list IS the count — no B x MB table rescan on
            # the hot per-window path
            short = sim_policy.grow_shortfall(
                (
                    (slot.admit_seq, row, slot.kv_ub,
                     len(self._slot_blocks[row]))
                    for row, slot in enumerate(self.slots) if slot.active
                ),
                k, horizon, self.block_size, self.MB,
            )  # (admit_seq, row, missing, have), oldest admissions first
            if not short:
                return
            ok = True
            for _, row, missing, have in short:
                try:
                    ids = self.kv_pool.alloc(missing)
                except PoolExhausted:
                    ok = False
                    break
                self._assign_row_blocks(row, ids, start_block=have)
                flight.emit(
                    "block_grow", self.slots[row].request_id,
                    blocks=missing, total=have + missing,
                )
            if ok:
                return
            # growth blocked: drop registered prefix blocks first (cache
            # refs are re-buildable; without this a lone active row whose
            # growth the registrations crowd out would preempt ITSELF in a
            # loop), then preempt the newest active row and retry.
            # Non-hot registrations go first — a warm chunk costs one
            # re-scatter to bring back, a hot one a proven-shared re-stage
            if self._chunk_regs:
                # chunk-canonical copies go before chain registrations:
                # they are rebuilt from the cache by any exact scatter
                self._drop_chunk_reg(next(iter(self._chunk_regs)))
                continue
            if self._prefix_blocks:
                self._drop_registration(sim_policy.reclaim_registration(
                    self._prefix_blocks, self._prefix_tier,
                    self._prefix_reg_gen,
                ))
                continue
            if self._chunk_admissions:
                # pending chunked admissions are the cheapest preemption
                # victims: ZERO emitted tokens to replay — the scheduler
                # resubmits them wholesale, and recompute is exactly the
                # prefill that hadn't happened yet. Newest-queued first,
                # matching the active-row discipline below.
                rid, rec = self._chunk_admissions.popitem()
                self._preempt_chunk_admission(rid, rec)
                continue
            _, victim = sim_policy.preempt_victim(
                (s.admit_seq, r) for r, s in enumerate(self.slots) if s.active
            )
            vslot = self.slots[victim]
            logger.warning(
                "kv pool exhausted mid-decode; preempting request %d "
                "(%d blocks back to the pool)",
                vslot.request_id, len(self._slot_blocks[victim]),
            )
            self._preempted.append((vslot.request_id, list(vslot.tokens)))
            self._m_pool_preempt.inc()
            flight.emit(
                "preempt", vslot.request_id,
                blocks=len(self._slot_blocks[victim]),
                n_tokens=len(vslot.tokens),
            )
            m = np.ones(self.B, bool)
            m[victim] = False
            self._active = self._active & self._put(jnp.asarray(m))
            self._release_row(victim)
            self.slots[victim] = _Slot()

    def _preempt_chunk_admission(self, rid: int, rec: dict) -> None:
        """Cancel an in-flight chunked admission under pool pressure: its
        partially-written blocks return to the pool and the request joins
        the preempted list with ZERO emitted tokens — the scheduler
        resubmits it (``_fold_emitted`` no-ops on the empty record), so
        the only cost is re-prefilling what this row had staged."""
        row = rec["row"]
        logger.warning(
            "kv pool exhausted; preempting chunked admission %d "
            "(%d blocks back to the pool, %d/%d prompt tokens staged)",
            rid, len(self._slot_blocks[row]), rec["progress"],
            len(rec["prompt"]),
        )
        self._preempted.append((rid, []))
        self._m_pool_preempt.inc()
        flight.emit(
            "preempt", rid,
            blocks=len(self._slot_blocks[row]), n_tokens=0,
        )
        self._release_row(row)
        self.slots[row] = _Slot()

    def drain_preempted(self) -> List[Tuple[int, List[int]]]:
        """Requests preempted by pool exhaustion since the last drain, as
        ``(request_id, emitted_tokens)`` — the scheduler resubmits them
        (prompt + emitted, budget reduced), so preemption is invisible to
        callers beyond latency."""
        if not self.paged or not self._preempted:
            return []
        out, self._preempted = self._preempted, []
        return out

    def pool_used_tokens(self) -> int:
        """Live logical tokens across UNIQUE pool blocks (host mirrors) —
        the numerator of the fragmentation gauge. Ref-shared prefix blocks
        count once, via their registration: each sharing row subtracts the
        tokens its table serves from shared blocks (a row whose
        registration was since dropped briefly over-reports fragmentation —
        a gauge-grade approximation, clamped by the pool)."""
        if not self.paged:
            return 0
        rows = sum(
            max(s.kv_ub - s.shared_tokens, 0) for s in self.slots if s.active
        )
        # the registration totals are single ints maintained on the
        # scheduler thread — iterating _prefix_blocks here would race the
        # scheduler's register/evict and crash a /metrics scrape with
        # "dictionary changed size during iteration"
        return rows + self._registered_tokens + self._chunk_reg_tokens

    # ------------------------------------------------------------------
    # operations (called by the scheduler thread only)
    # ------------------------------------------------------------------
    def free_slots(self) -> List[int]:
        # a prefilling row is reserved by an in-flight chunked admission:
        # not decoding yet, but not admissible either
        return [
            i for i, s in enumerate(self.slots)
            if not s.active and not s.prefilling
        ]

    def has_active(self) -> bool:
        # prefilling rows count: the scheduler must keep stepping (mixed
        # windows are what advances them), and admission_state must say
        # "wait", not "never", while they hold pool blocks
        return any(s.active or s.prefilling for s in self.slots)

    def evict_requests(self, request_ids: Sequence[int]) -> List[int]:
        """Deactivate the slots serving ``request_ids`` (deadline eviction):
        the rows stop attending/advancing on the very next step and their
        slots are immediately admissible again. Reuses the same deactivate
        mask the budget-retire path applies after ``step()`` — eviction is
        a retire without a result. Returns the freed row indices."""
        wanted = set(request_ids)
        rows = [
            i for i, s in enumerate(self.slots)
            if s.active and s.request_id in wanted
        ]
        if rows:
            m = np.ones(self.B, bool)
            m[rows] = False
            self._active = self._active & self._put(jnp.asarray(m))
            for r in rows:
                flight.emit(
                    "evict", self.slots[r].request_id,
                    n_tokens=len(self.slots[r].tokens),
                )
            self._retire_rows(rows)  # paged: blocks back to the free list
            for r in rows:
                self.slots[r] = _Slot()
        # in-flight chunked admissions are evictable too — the deadline
        # sweep sees them in `waiting` like any decoding request, and their
        # partially-written blocks must go back or eviction leaks the pool
        for rid in [r for r in self._chunk_admissions if r in wanted]:
            rec = self._chunk_admissions.pop(rid)
            row = rec["row"]
            flight.emit("evict", rid, n_tokens=0)
            self._blocks_at_retire[rid] = len(self._slot_blocks[row])
            self._release_row(row)
            self.slots[row] = _Slot()
            rows.append(row)
        return rows

    def admit(
        self,
        request_id: int,
        prompt: Sequence[int],
        max_new: int,
        seed: Optional[int] = None,
    ) -> Tuple[int, Optional[List[int]]]:
        """Prefill + insert into a free slot. Returns ``(slot, finished)``;
        ``finished`` is the complete token list when the request ends at its
        very first token (EOS or max_new=1) without keeping the slot."""
        res = self.admit_many([(request_id, prompt, max_new, seed)])[0]
        if isinstance(res, BaseException):
            raise res
        return res

    def admit_many(
        self, items: Sequence[Tuple[int, Sequence[int], int, Optional[int]]]
    ) -> List[Tuple[int, Optional[List[int]]]]:
        """Admit a GROUP of requests: same-bucket requests prefill together
        (one batched forward), splice into their slots in one insert call,
        and their first tokens return in ONE device→host fetch — the
        per-admission round-trip (the continuous engine's biggest cost on a
        slow host link) amortizes over the group. Returns ``(slot,
        finished)`` per item, input order.

        Per item: the prompt is bucketed over the FULL bucket ladder and
        ``max_new`` is clamped to the remaining cache room (mirroring
        ``InferenceEngine._clamp_max_new``) — the prompt is never cut to
        make room for generation. Only a prompt over the largest bucket
        truncates, loudly (continuous slots are fixed-length; route such
        prompts through ``InferenceEngine``'s chunked prefill instead).
        Draws stay (seed, position)-keyed per row, so admission grouping
        never changes what a request samples.

        Failure isolation: a failed CHUNK fails only its own items — their
        result entries are the exception instance (callers re-raise or
        deliver per item); earlier chunks' admissions stand.
        ``EngineStateLost`` is the exception to that: the reset wiped every
        slot, so it propagates out of the whole call."""
        free = self.free_slots()
        assert len(items) <= len(free), "admit_many() without enough free slots"
        # the FIRST chunk's ledger window absorbs this call's prep (per-item
        # key derivation is device work too) — without it, the per-request
        # chip-second sums drift below the scheduler's measured busy time
        # and the conservation invariant frays at small window counts
        self._admit_lead = time.perf_counter()

        prepared = []  # (item_idx, rid, S, p, max_new_c, row_key)
        for i, (rid, prompt, max_new, seed) in enumerate(items):
            S = sim_policy.bucket_len(max(len(prompt), 1), self.buckets)
            max_new_c = sim_policy.clamp_max_new(max_new, S, self.T)
            p = list(prompt)[-S:]
            if len(prompt) > S:
                logger.warning(
                    "continuous-batch prompt of %d tokens exceeds the largest "
                    "bucket %d; left-truncating", len(prompt), S,
                )
            if seed is not None:
                row_key = jax.random.PRNGKey(seed)
            else:
                self._rng, row_key = jax.random.split(self._rng)
            prepared.append((i, rid, S, p, max_new_c, row_key))

        if self.interleave_on and self.paged:
            # unified ragged windows (ISSUE 16): admission is INCREMENTAL —
            # reserve a row and queue the prompt; mixed windows feed it in
            # budgeted chunks alongside decode. No prefill forward, no
            # up-front block allocation (the planner allocates per chunk),
            # so this path cannot raise PoolExhausted. The prep above ran
            # UNCHANGED — same bucketing/truncation/clamp and the same
            # ``self._rng`` split order, so streams bit-match the
            # phase-separated scheduler.
            results = [None] * len(items)
            free_iter = iter(free)
            for i, rid, S, p, max_new_c, row_key in prepared:
                self._queue_chunk_admission(
                    i, rid, S, p, max_new_c, row_key,
                    next(free_iter), results,
                )
            return results

        results: List = [None] * len(items)
        free_iter = iter(free)
        # same-bucket grouping in pow2 chunks (warmup-friendly executable
        # ladder), arrival order preserved — the decision core plans it
        for S, member_idx in sim_policy.admission_chunks(
            [(j, entry[2]) for j, entry in enumerate(prepared)], self.B
        ):
            chunk = [prepared[j] for j in member_idx]
            rows = [next(free_iter) for _ in chunk]
            try:
                self._admit_chunk(S, chunk, rows, results)
            except EngineStateLost:
                raise  # slots are gone for EVERYONE; callers must fail
            except BaseException as e:  # noqa: BLE001 — per-chunk isolation
                for i, _, _, _, _, _ in chunk:
                    results[i] = e
        return results

    def _admit_chunk_t0(self) -> float:
        """This chunk's ledger-window start: the admit_many call's entry
        stamp for the first chunk (prep absorbed), now for the rest."""
        lead = getattr(self, "_admit_lead", None)
        if lead is not None:
            self._admit_lead = None
            return lead
        return time.perf_counter()

    def _admit_chunk(self, S: int, chunk, rows: List[int], results: List):
        """One batched prefill + insert + first-token fetch for ``chunk``."""
        if self.paged:
            return self._admit_chunk_paged(S, chunk, rows, results)
        t_led = self._admit_chunk_t0()  # ledger window (prep absorbed)
        t_admit = time.perf_counter()  # _m_step_admit keeps chunk-only
        n = len(chunk)
        tokens = np.full((n, S), self.pad_id, np.int32)
        mask = np.zeros((n, S), np.int32)
        folded_keys, base_keys = [], []
        for r, (_, _, _, p, _, row_key) in enumerate(chunk):
            tokens[r, S - len(p):] = p
            mask[r, S - len(p):] = 1
            # position-indexed draw: the first sampled token sits at position
            # len(p); decode steps continue the same fold sequence. Keys STAY
            # on device — fetching them here would put one host round-trip
            # per request back on the admission path the batching removed
            folded_keys.append(jax.random.fold_in(row_key, len(p)))
            base_keys.append(row_key)
        folded = jnp.stack(folded_keys)
        row_keys = jnp.stack(base_keys)

        row_cache, tok0s, row_starts = self._get("prefill", S, n)(
            self.params, self._put(tokens), self._put(mask), self._put(folded)
        )
        try:
            # fault site "insert": models a device fault inside the donated
            # splice — the handler below must reset and raise EngineStateLost
            faults.maybe_fail("insert")
            # insert dispatches BEFORE the tok0 fetch: the splice runs on
            # device while the first tokens cross the host link
            (self._cache, self._kv_start, self._kv_len,
             self._last_tok, self._active, self._rng_keys) = self._get("insert", S, n)(
                self._cache, row_cache,
                self._kv_start, self._kv_len, self._last_tok, self._active,
                self._rng_keys, self._put(np.asarray(rows, np.int32)),
                row_starts, tok0s, self._put(row_keys),
            )
        except BaseException as e:  # noqa: BLE001
            # insert donates the engine's cache/state buffers: a failure
            # mid-execution has invalidated them even though nothing was
            # reassigned — rebuild now, or every later admit serves
            # "Array has been deleted" while /healthz stays green
            self.reset()
            raise EngineStateLost("insert failed; engine state reset") from e

        try:
            tok0_h = np.asarray(tok0s)  # ONE fetch for the whole chunk
            self._m_step_admit.observe(time.perf_counter() - t_admit)
            deactivate = []
            for r, (i, rid, _, p, max_new_c, _) in enumerate(chunk):
                tok0 = int(tok0_h[r])
                row = rows[r]
                self.stats.generate_calls += 1
                self.stats.prefill_tokens += len(p)
                flight.emit(
                    "admit", rid, slot=row, prompt_len=len(p), bucket=S,
                    tok0=tok0, **_tenant_attr(self.ledger, rid),
                )
                if tok0 in self.config.eos_token_ids or max_new_c <= 1:
                    # finished at its very first token: the slot was spliced
                    # active by the batched insert — release it on device too
                    out = [] if tok0 in self.config.eos_token_ids else [tok0]
                    self.stats.decode_tokens += len(out)
                    deactivate.append(row)
                    results[i] = (row, out)
                    continue
                self.slots[row] = _Slot(
                    request_id=rid, tokens=[tok0], remaining=max_new_c - 1,
                    active=True,
                )
                self.stats.decode_tokens += 1  # tok0, sampled at prefill
                results[i] = (row, None)
            if deactivate:
                m = np.ones(self.B, bool)
                m[deactivate] = False
                self._active = self._active & self._put(jnp.asarray(m))
            led_rows = {rid: len(p) for _, rid, _, p, _, _ in chunk}
            self._journal_window(self.ledger.record_prefill(
                time.perf_counter() - t_led, bucket=S, rows=led_rows,
                rework=self._take_rework(led_rows),
            ))
        except BaseException:  # noqa: BLE001 — release before isolation
            # the insert already spliced these rows device-active; failing
            # here (e.g. the tok0 fetch) would otherwise leave them decoding
            # garbage every step with no host _Slot to ever retire them —
            # deactivate the whole chunk's rows and drop any _Slot entries
            # made above, THEN let admit_many's per-chunk isolation handle it
            m = np.ones(self.B, bool)
            m[rows] = False
            self._active = self._active & self._put(jnp.asarray(m))
            for row in rows:
                self.slots[row] = _Slot()  # fresh inactive slot
            raise

    def _admit_chunk_paged(self, S: int, chunk, rows: List[int], results: List):
        """Paged twin of ``_admit_chunk``: allocate each row's blocks, one
        RIGHT-padded batched prefill, one scatter-insert into the arena —
        no per-row ``(S,)`` cache splice survives past the insert call.
        ``PoolExhausted`` during allocation is backpressure, not failure:
        already-taken blocks return and the exception propagates so the
        scheduler can requeue the chunk's items."""
        t_led = self._admit_chunk_t0()  # ledger window (prep absorbed)
        t_admit = time.perf_counter()  # _m_step_admit keeps chunk-only
        n = len(chunk)
        bs = self.block_size
        nb = S // bs
        taken: List[Tuple[int, List[int]]] = []  # (row, ids)
        block_ids = np.zeros((n, nb), np.int32)  # NULL beyond a row's need
        lens = np.zeros((n,), np.int32)
        try:
            for r, (_, _, _, p, _, _) in enumerate(chunk):
                need = self.kv_pool.blocks_for(max(len(p), 1))
                ids = self.kv_pool.alloc(need)
                taken.append((rows[r], ids))
                block_ids[r, : len(ids)] = ids
                lens[r] = len(p)
        except PoolExhausted:
            for _, ids in taken:
                self.kv_pool.free(ids)
            # the bounced chunk cost real scheduler time (per-item key
            # prep is device work): attribute the failed attempt to its
            # requests — they requeue, and without this the conservation
            # invariant frays under sustained pool pressure
            self._journal_window(self.ledger.record_preempt_stall(
                time.perf_counter() - t_led,
                [c[1] for c in chunk], kind="prefill",
            ))
            raise
        tokens = np.full((n, S), self.pad_id, np.int32)
        folded_keys, base_keys = [], []
        for r, (_, _, _, p, _, row_key) in enumerate(chunk):
            tokens[r, : len(p)] = p  # RIGHT-padded: logical positions 0..len
            # same (seed, position) fold as the dense path: the first
            # sampled token sits at canonical position len(p) either way
            folded_keys.append(jax.random.fold_in(row_key, len(p)))
            base_keys.append(row_key)
        folded = jnp.stack(folded_keys)
        row_keys = jnp.stack(base_keys)

        for row, ids in taken:
            self._assign_row_blocks(row, ids)
        self._device_tables()  # refresh before anything can step

        try:
            row_cache, tok0s = self._get("prefill_paged", S, n)(
                self.params, self._put(tokens), self._put(jnp.asarray(lens)),
                self._put(folded),
            )
        except BaseException:  # noqa: BLE001 — nothing donated yet
            # the prefill touches none of the engine's donated state, so
            # per-chunk isolation is enough — but the blocks taken above
            # must go back and the tables re-null, or a one-off device
            # error becomes a permanent pool leak on inactive rows
            for row, _ in taken:
                self._release_row(row)
            raise
        try:
            faults.maybe_fail("insert")
            (self._cache, self._kv_len, self._last_tok,
             self._active, self._rng_keys) = self._get("insert_paged", S, n)(
                self._cache, row_cache,
                self._kv_len, self._last_tok, self._active, self._rng_keys,
                self._put(np.asarray(rows, np.int32)),
                self._put(jnp.asarray(block_ids)),
                self._put(jnp.asarray(lens)), tok0s, self._put(row_keys),
            )
        except BaseException as e:  # noqa: BLE001 — donated arena invalidated
            self.reset()
            raise EngineStateLost("insert failed; engine state reset") from e

        try:
            tok0_h = np.asarray(tok0s)  # ONE fetch for the whole chunk
            self._m_step_admit.observe(time.perf_counter() - t_admit)
            deactivate = []
            for r, (i, rid, _, p, max_new_c, _) in enumerate(chunk):
                tok0 = int(tok0_h[r])
                row = rows[r]
                self.stats.generate_calls += 1
                self.stats.prefill_tokens += len(p)
                flight.emit(
                    "admit", rid, slot=row, prompt_len=len(p), bucket=S,
                    tok0=tok0, **_tenant_attr(self.ledger, rid),
                )
                if tok0 in self.config.eos_token_ids or max_new_c <= 1:
                    out = [] if tok0 in self.config.eos_token_ids else [tok0]
                    self.stats.decode_tokens += len(out)
                    deactivate.append(row)
                    self._blocks_at_retire[rid] = len(self._slot_blocks[row])
                    self._release_row(row)
                    results[i] = (row, out)
                    continue
                self._admit_seq += 1
                self.slots[row] = _Slot(
                    request_id=rid, tokens=[tok0], remaining=max_new_c - 1,
                    active=True, kv_ub=len(p), admit_seq=self._admit_seq,
                    prompt_len=len(p),
                    # spec draft corpus: the full assembled prompt (head +
                    # retrieved chunks arrive through the scheduler as one
                    # token list) + the first sampled token
                    history=(list(p) + [tok0]) if self.spec_on else [],
                )
                self.stats.decode_tokens += 1
                results[i] = (row, None)
            if deactivate:
                m = np.ones(self.B, bool)
                m[deactivate] = False
                self._active = self._active & self._put(jnp.asarray(m))
            led_rows = {rid: len(p) for _, rid, _, p, _, _ in chunk}
            self._journal_window(self.ledger.record_prefill(
                time.perf_counter() - t_led, bucket=S, rows=led_rows,
                rework=self._take_rework(led_rows),
            ))
        except BaseException:  # noqa: BLE001 — release before isolation
            m = np.ones(self.B, bool)
            m[rows] = False
            self._active = self._active & self._put(jnp.asarray(m))
            for row in rows:
                self._release_row(row)
                self.slots[row] = _Slot()
            raise

    def _queue_chunk_admission(
        self, i: int, rid: int, S: int, p: List[int], max_new_c: int,
        row_key, row: int, results: List,
    ) -> None:
        """Reserve ``row`` for an incremental admission and queue its
        record — zero device work. The row's UNFOLDED key is staged now
        (the ``insert_paged`` idiom): the final chunk's executable folds
        ``(row_key, len(p))`` from it, and decode continues the same fold
        sequence once the row activates."""
        self._admit_seq += 1
        self._rng_keys = self._rng_keys.at[row].set(self._put(row_key))
        self.slots[row] = _Slot(
            request_id=rid, prefilling=True, admit_seq=self._admit_seq,
            prompt_len=len(p),
        )
        self._chunk_admissions[rid] = {
            "row": row, "prompt": p, "progress": 0, "row_key": row_key,
            "max_new": max_new_c, "bucket": S, "admit_seq": self._admit_seq,
            # TTFT anchors: the scheduler overwrites t_submit with the
            # request's real submit stamp (or None for retries/resumes,
            # which never observe TTFT — phase-separated parity); raw
            # engine callers fall back to the queue stamp
            "t_admit": time.monotonic(),
        }
        results[i] = (row, None)

    # ------------------------------------------------------------------
    # prefill→decode migration (disaggregated pools; ISSUE 20)
    # ------------------------------------------------------------------
    def export_request(self, request_id: int) -> Optional[dict]:
        """Pull a just-admitted request OFF this engine as a migration
        packet: its pool blocks' planes (one non-donating gather), its
        sampling state (kv_len, last token, UNFOLDED rng key) and its
        budget — everything a decode-role twin's ``import_request``
        needs to continue the stream byte-identically. The source row is
        released before returning (blocks back to the pool, device row
        deactivated), so after a successful export this engine holds
        NOTHING for the request. Returns None when the request is not
        exportable (unknown, still chunk-prefilling, already finished) —
        the scheduler then keeps decoding it locally. A gather failure
        propagates with the engine fully intact (nothing was donated)."""
        if not self.paged:
            return None
        if request_id in self._chunk_admissions:
            # interleaved admission still staging: no tok0 yet, nothing
            # to hand off — the mixed windows will finish it locally
            return None
        row = next(
            (i for i, s in enumerate(self.slots)
             if s.active and s.request_id == request_id), None,
        )
        if row is None:
            return None
        slot = self.slots[row]
        ids = list(self._slot_blocks[row])
        S = sim_policy.bucket_len(max(slot.prompt_len, 1), self.buckets)
        nb_pad = S // self.block_size
        padded = ids + [NULL_BLOCK] * (nb_pad - len(ids))
        t0 = time.perf_counter()
        # the row's base key: ONE tiny ([2] uint32) fetch per migration —
        # the decode twin must seed its row with the UNFOLDED key so its
        # step fold sequence continues exactly where admission left off
        row_key = np.asarray(self._rng_keys[row])
        planes = self._get("migrate_out", nb_pad)(
            self._cache, self._put(jnp.asarray(np.asarray(padded, np.int32)))
        )
        packet = {
            "request_id": request_id,
            "planes": planes,
            "n_blocks": len(ids),
            "nb_pad": nb_pad,
            "kv_len": slot.kv_ub,
            "tokens": list(slot.tokens),
            "remaining": slot.remaining,
            "prompt_len": slot.prompt_len,
            "row_key": row_key,
            "history": list(slot.history) if self.spec_on else [],
        }
        # the gather succeeded: NOW release the source side — record the
        # footprint first (the scheduler forwards it into the timings)
        self._blocks_at_retire[request_id] = len(ids)
        m = np.ones(self.B, bool)
        m[row] = False
        self._active = self._active & self._put(jnp.asarray(m))
        self._release_row(row)
        self.slots[row] = _Slot()
        flight.emit(
            "migrate_begin", request_id, blocks=len(ids),
            kv_len=packet["kv_len"],
            duration_ms=round((time.perf_counter() - t0) * 1e3, 3),
            **_tenant_attr(self.ledger, request_id),
        )
        return packet

    def import_request(self, packet: dict) -> int:
        """Land a migrated packet in a fresh row: allocate destination
        blocks (``PoolExhausted`` propagates BEFORE anything is donated —
        the packet stays valid and the scheduler requeues it), scatter
        the planes + splice the sampling state in one donating call, and
        rebuild the host slot so decode continues the same (seed,
        position) fold sequence. A failure inside the donating call
        resets this engine (``EngineStateLost``) — the scheduler
        re-prefills prompt+emitted here, streams still byte-identical.
        Returns the row index."""
        assert self.paged, "import_request() requires kv_paged=True"
        free = self.free_slots()
        assert free, "import_request() without a free slot"
        rid = packet["request_id"]
        n_real = packet["n_blocks"]
        nb_pad = packet["nb_pad"]
        ids = self.kv_pool.alloc(n_real)  # PoolExhausted = backpressure
        row = free[0]
        dst = ids + [NULL_BLOCK] * (nb_pad - n_real)
        t0 = time.perf_counter()
        self._assign_row_blocks(row, ids)
        self._device_tables()  # refresh before anything can step
        try:
            # fault site "migrate": a device fault inside the donated
            # import — the decode engine resets and the scheduler
            # re-prefills prompt+emitted (docs/ROUTER.md)
            faults.maybe_fail("migrate")
            (self._cache, self._kv_len, self._last_tok,
             self._active, self._rng_keys) = self._get("migrate_in", nb_pad)(
                self._cache, packet["planes"],
                self._kv_len, self._last_tok, self._active, self._rng_keys,
                self._put(jnp.int32(row)),
                self._put(jnp.asarray(np.asarray(dst, np.int32))),
                self._put(jnp.int32(packet["kv_len"])),
                self._put(jnp.int32(packet["tokens"][-1])),
                self._put(jnp.asarray(packet["row_key"])),
            )
        except BaseException as e:  # noqa: BLE001 — donated arena invalidated
            self.reset()
            raise EngineStateLost(
                "migrate import failed; engine state reset"
            ) from e
        self._admit_seq += 1
        self.slots[row] = _Slot(
            request_id=rid, tokens=list(packet["tokens"]),
            remaining=packet["remaining"], active=True,
            kv_ub=packet["kv_len"], admit_seq=self._admit_seq,
            prompt_len=packet["prompt_len"],
            history=list(packet["history"]) if self.spec_on else [],
        )
        flight.emit(
            "migrate_done", rid, slot=row, blocks=n_real,
            kv_len=packet["kv_len"],
            duration_ms=round((time.perf_counter() - t0) * 1e3, 3),
            **_tenant_attr(self.ledger, rid),
        )
        return row

    def _alloc_chunk_blocks(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` blocks for a scheduled prefill chunk, reclaiming
        re-buildable registrations under pressure in ``admission_state``'s
        order: chunk-canonical copies first, then non-hot prefix chains,
        then — only when nothing decodes — hot chains. ``None`` = the pool
        really is full; the planner idles the admission this window."""
        while True:
            try:
                return self.kv_pool.alloc(n)
            except PoolExhausted:
                if self._chunk_regs:
                    self._drop_chunk_reg(next(iter(self._chunk_regs)))
                    continue
                non_hot = [
                    k for k, t in list(self._prefix_tier.items())
                    if t != "hot"
                ]
                if non_hot:
                    self._drop_registration(non_hot[0])
                    continue
                if self._prefix_blocks and not self.has_active():
                    self._drop_registration(next(iter(self._prefix_blocks)))
                    continue
                return None

    def _step_mixed(self) -> List[Tuple[int, List[int]]]:
        """One UNIFIED ragged sync window (ISSUE 16): every active decode
        lane advances one token while a budgeted slice of each pending
        chunked admission prefills through the SAME device call — decode
        never stops for admission, and a long prompt spreads its prefill
        across as many windows as its chunks.

        Budget split: active decode lanes cost one token each; the
        remainder slices pending admissions FIFO (oldest first — the
        request closest to its first token wins the window's leftover).
        Each scheduled chunk allocates only ITS blocks (incremental — the
        one-shot path pays the whole prompt up front); pool pressure
        reclaims re-buildable registrations, then idles the youngest
        admissions for the window.

        The drain mirrors the two phase-separated paths exactly: decode
        rows drain like a ``k=1`` plain window, final chunks run
        ``_admit_chunk_paged``'s tail (admit event, EOS/max_new<=1
        immediate retire, fresh active ``_Slot`` otherwise) — so streams,
        events and block accounting are indistinguishable downstream."""
        C = self.chunk_tokens
        t_w = time.perf_counter()  # ledger window: planning + growth included
        Tmax = self.MB * self.block_size
        # map decode lanes' one write each BEFORE dispatch; exhaustion here
        # preempts pending chunked admissions before any decoding row
        self._ensure_decode_blocks(horizon={})
        n_dec = sum(1 for s in self.slots if s.active)
        # the budget split (decode lanes first, remainder FIFO over the
        # pending admissions in chunk_tokens slices) is the decision
        # core's; this loop only stages each slice's blocks, idling the
        # younger admissions at the first slice the pool cannot take
        sched = []  # (rid, rec, offset, take, final)
        for rid, off, take, final in sim_policy.plan_mixed_window(
            [(rid, len(rec["prompt"]), rec["progress"])
             for rid, rec in self._chunk_admissions.items()],
            self.window_budget, n_dec, C,
        ):
            rec = self._chunk_admissions[rid]
            row = rec["row"]
            need = self.kv_pool.blocks_for(off + take)
            have = len(self._slot_blocks[row])
            if need > have:
                ids = self._alloc_chunk_blocks(need - have)
                if ids is None:
                    break  # pool pressure: idle the rest this window
                self._assign_row_blocks(row, ids, start_block=have)
            sched.append((rid, rec, off, take, final))
        flight.emit(
            "window_budget", budget=self.window_budget, decode_lanes=n_dec,
            chunk_tokens=sum(t for _, _, _, t, _ in sched),
            chunks=len(sched), queued=len(self._chunk_admissions),
        )
        for rid, rec, off, take, final in sched:
            flight.emit(
                "prefill_chunk_sched", rid, offset=off, tokens=take,
                remaining=len(rec["prompt"]) - off - take, final=int(final),
            )
        if not sched and n_dec == 0:
            # the pool can't stage even the oldest admission and nothing
            # decodes: make room by preempting the newest (the scheduler
            # resubmits; the admission_state gate re-screens impossible
            # prompts) instead of spinning an empty window
            if self._chunk_admissions:
                vrid, vrec = self._chunk_admissions.popitem()
                self._preempt_chunk_admission(vrid, vrec)
            self._journal_window(self.ledger.record_preempt_stall(
                time.perf_counter() - t_w,
                [r for r, _ in self._preempted], kind="prefill",
            ))
            return []
        flight.emit(
            "sync_window_open", steps=1, active=n_dec + len(sched),
        )
        fed = np.full((self.B, C), self.pad_id, np.int32)
        n_fed = np.zeros((self.B,), np.int32)
        chunk_base = np.zeros((self.B,), np.int32)
        final_v = np.zeros((self.B,), bool)
        for rid, rec, off, take, final in sched:
            row = rec["row"]
            fed[row, :take] = rec["prompt"][off : off + take]
            n_fed[row] = take
            chunk_base[row] = off
            final_v[row] = final
        # context tokens resident at dispatch: decode rows' frontiers plus
        # each chunk's attended prefix (its own slice included)
        ctx = sum(s.kv_ub for s in self.slots if s.active) + sum(
            off + take for _, _, off, take, _ in sched
        )
        t0 = time.perf_counter()
        (self._cache, self._kv_len, self._last_tok, toks, eoss,
         self._active) = self._get("mixed_step", C)(
            self.params, self._cache, self._device_tables(),
            self._kv_len, self._last_tok, self._active, self._rng_keys,
            self._put(jnp.asarray(fed)), self._put(jnp.asarray(n_fed)),
            self._put(jnp.asarray(chunk_base)),
            self._put(jnp.asarray(final_v)),
        )
        self.steps += 1
        tok_h = np.asarray(toks)  # [B] — ONE fetch for decode AND admissions
        t_fetch = time.perf_counter()
        self._m_itl.observe(t_fetch - t0)
        self._m_step_device.observe(t_fetch - t0)
        eos_h = np.asarray(eoss)
        for slot in self.slots:
            if slot.active:
                slot.kv_ub = min(slot.kv_ub + 1, Tmax - 1)
        done: List[Tuple[int, List[int]]] = []
        deactivate = []
        kept: Dict[int, int] = {}  # rid -> decode tokens kept (ledger)
        # ---- decode lanes: exactly a k=1 plain-window drain --------------
        for i, slot in enumerate(self.slots):
            if not slot.active:
                continue
            finished = False
            kept[slot.request_id] = 0
            if eos_h[i]:
                finished = True  # EOS token itself is not emitted
            else:
                slot.tokens.append(int(tok_h[i]))
                if self.spec_on:
                    slot.history.append(int(tok_h[i]))
                slot.remaining -= 1
                self.stats.decode_tokens += 1
                kept[slot.request_id] += 1
                if slot.remaining <= 0:
                    finished = True
            if finished:
                done.append((slot.request_id, slot.tokens))
                flight.emit(
                    "eos", slot.request_id,
                    reason="budget" if slot.remaining <= 0 else "eos",
                    n_tokens=len(slot.tokens),
                )
                slot.active = False
                deactivate.append(i)
        # ---- chunk rows: progress, and _admit_chunk_paged's tail on the
        # final chunk --------------------------------------------------
        chunk_led: Dict[int, int] = {}  # rid -> real prefill lanes (ledger)
        finished_rows: List[int] = []
        for rid, rec, off, take, final in sched:
            row = rec["row"]
            rec["progress"] = off + take
            chunk_led[rid] = take
            if not final:
                continue
            tok0 = int(tok_h[row])
            p = rec["prompt"]
            max_new_c = rec["max_new"]
            del self._chunk_admissions[rid]
            self.stats.generate_calls += 1
            self.stats.prefill_tokens += len(p)
            flight.emit(
                "admit", rid, slot=row, prompt_len=len(p),
                bucket=rec["bucket"], tok0=tok0,
                **_tenant_attr(self.ledger, rid),
            )
            ts = rec.get("t_submit", rec["t_admit"])
            if ts is not None:
                self._m_ttft.observe(time.monotonic() - ts)
            if tok0 in self.config.eos_token_ids or max_new_c <= 1:
                out = [] if tok0 in self.config.eos_token_ids else [tok0]
                self.stats.decode_tokens += len(out)
                done.append((rid, out))
                # the executable left an EOS'd final inactive; the budget
                # case it activated — mask either way, and retire via the
                # common tail (the slot still carries rid for the footprint)
                deactivate.append(row)
                finished_rows.append(row)
                continue
            self.slots[row] = _Slot(
                request_id=rid, tokens=[tok0], remaining=max_new_c - 1,
                active=True, kv_ub=len(p), admit_seq=rec["admit_seq"],
                prompt_len=len(p),
                history=(list(p) + [tok0]) if self.spec_on else [],
            )
            self.stats.decode_tokens += 1
        if deactivate:
            mask = np.ones(self.B, bool)
            mask[deactivate] = False
            self._active = self._active & self._put(jnp.asarray(mask))
            self._retire_rows(deactivate)  # blocks back + footprint record
        for row in finished_rows:
            self.slots[row] = _Slot()  # clear the prefilling reservation
        self._m_step_drain.observe(time.perf_counter() - t_fetch)
        self._journal_window(self.ledger.record_mixed(
            time.perf_counter() - t_w, batch=self.B, lanes=C,
            decode_kept=kept, chunk_rows=chunk_led,
            rework=self._take_rework(chunk_led), ctx_tokens=ctx,
        ))
        self._journal_emitted()
        flight.emit(
            "sync_window_close", steps=1, done=len(done),
            duration_ms=round((time.perf_counter() - t0) * 1e3, 3),
        )
        return done

    def step(self) -> List[Tuple[int, List[int]]]:
        """``decode_sync_steps`` decode steps for every active slot in one
        device call + one host fetch. Returns completed requests as
        ``(request_id, tokens)`` and frees their slots.

        With ``spec_paged`` enabled, a window where drafting is expected
        to WIN runs as ONE multi-token verify step instead
        (``_step_verify`` — up to ``spec_K + 1`` tokens retired per row
        per fetch). The routing is throughput-gated, not draft-gated: a
        verify call retires ``1 + accepted`` tokens per row while a plain
        window retires ``sync_steps`` per row, so one persistently-
        quoting row in a large batch must not collapse the k-step
        amortization for every non-drafting batchmate
        (``_verify_worthwhile``). Windows that don't clear the bar (and
        all no-draft windows) keep the plain path untouched."""
        faults.maybe_fail("decode_step")
        if self.interleave_on and self.paged and self._chunk_admissions:
            # unified ragged window: pending chunked admissions ride along
            # with decode; speculation resumes once the queue drains (both
            # window shapes are draw-invariant, so streams never notice
            # the handoff)
            return self._step_mixed()
        if self.spec_on and self.paged:
            drafts = self._draft_for_slots()
            if any(drafts.values()) and self._verify_worthwhile(drafts):
                return self._step_verify(drafts)
        k = self.sync_steps
        t_w = time.perf_counter()  # ledger window: block growth included
        if self.paged:
            # map the blocks this window will write BEFORE dispatch (an
            # unmapped write vanishes into the null block and corrupts the
            # stream one step later); exhaustion preempts the newest rows
            self._ensure_decode_blocks()
            if not self.has_active():
                # everything was preempted: nothing to step — but the
                # scheduler WAS busy preempting; attribute the stall to
                # the preempted requests or conservation frays in storms
                self._journal_window(self.ledger.record_preempt_stall(
                    time.perf_counter() - t_w,
                    [rid for rid, _ in self._preempted],
                ))
                return []
        flight.emit(
            "sync_window_open", steps=k,
            active=sum(1 for s in self.slots if s.active),
        )
        # context tokens resident at dispatch (paged host mirror) — the
        # decode window's KV-read bytes in the roofline estimate
        ctx = sum(s.kv_ub for s in self.slots if s.active) if self.paged else 0
        t0 = time.perf_counter()
        if self.paged:
            (self._cache, self._kv_len, self._last_tok, toks, eoss,
             self._active) = self._get("step_paged", k)(
                self.params, self._cache, self._device_tables(),
                self._kv_len, self._last_tok, self._active, self._rng_keys,
            )
            Tmax = self.MB * self.block_size
            for slot in self.slots:
                if slot.active:
                    slot.kv_ub = min(slot.kv_ub + k, Tmax - 1)
        else:
            (self._cache, self._kv_len, self._last_tok, toks, eoss,
             self._active) = self._get("step", k)(
                self.params, self._cache, self._kv_start,
                self._kv_len, self._last_tok, self._active, self._rng_keys,
            )
        self.steps += k
        tok_h = np.asarray(toks)  # [k, B]
        # EXACT inter-token latency: one sync window (device step + the
        # token-plane fetch) amortized over its k steps — every active row
        # advanced k tokens in this wall-clock interval
        t_fetch = time.perf_counter()
        self._m_itl.observe((t_fetch - t0) / k)
        self._m_step_device.observe(t_fetch - t0)
        eos_h = np.asarray(eoss)
        done: List[Tuple[int, List[int]]] = []
        deactivate = []
        kept: Dict[int, int] = {}  # rid -> tokens this window kept (ledger)
        for i, slot in enumerate(self.slots):
            if not slot.active:
                continue
            finished = False
            kept[slot.request_id] = 0
            for j in range(k):
                if eos_h[j, i]:
                    finished = True  # EOS token itself is not emitted
                    break
                slot.tokens.append(int(tok_h[j, i]))
                if self.spec_on:
                    slot.history.append(int(tok_h[j, i]))
                slot.remaining -= 1
                self.stats.decode_tokens += 1
                kept[slot.request_id] += 1
                if slot.remaining <= 0:
                    finished = True  # later window tokens (if any) discarded
                    break
            if finished:
                done.append((slot.request_id, slot.tokens))
                flight.emit(
                    "eos", slot.request_id,
                    reason="budget" if slot.remaining <= 0 else "eos",
                    n_tokens=len(slot.tokens),
                )
                slot.active = False
                deactivate.append(i)
        if deactivate:
            # rows that hit their budget (not EOS) must stop decoding on
            # device too; EOS rows were already deactivated in-step
            mask = np.ones(self.B, bool)
            mask[deactivate] = False
            self._active = self._active & self._put(jnp.asarray(mask))
            self._retire_rows(deactivate)  # paged: blocks back to the pool
        self._m_step_drain.observe(time.perf_counter() - t_fetch)
        self._journal_window(self.ledger.record_decode(
            time.perf_counter() - t_w, batch=self.B, steps=k,
            kept=kept, ctx_tokens=ctx,
        ))
        self._journal_emitted()
        flight.emit(
            "sync_window_close", steps=k, done=len(done),
            duration_ms=round((time.perf_counter() - t0) * 1e3, 3),
        )
        return done

    # ------------------------------------------------------------------
    # speculative decoding (spec_paged; docs/SPECULATIVE.md)
    # ------------------------------------------------------------------
    def _draft_for_slots(self) -> Dict[int, List[int]]:
        """This window's draft per active row: prompt-lookup over the
        row's own history (assembled prompt + emitted — the retrieved
        chunks ARE the corpus), length-capped by the row's decayed
        acceptance EMA (low-acceptance rows degrade to K=1;
        engine/speculative.py), its remaining token budget (tokens past
        it are discarded anyway) and the slot ladder's top (a draft whose
        accepted frontier would overrun ``Tmax`` can't be mapped). An
        empty list means the row takes a plain decode step — inside the
        verify window when batchmates drafted, on the ordinary sync-step
        path when nobody did."""
        Tmax = self.MB * self.block_size
        out: Dict[int, List[int]] = {}
        for row, slot in enumerate(self.slots):
            if not slot.active:
                continue
            k_row = adaptive_draft_len(
                slot.spec_ema, self.spec_K, self.spec_min_accept
            )
            k_row = min(k_row, slot.remaining - 1, Tmax - 2 - slot.kv_ub)
            if k_row < 1:
                out[row] = []
                continue
            out[row] = prompt_lookup_draft(
                slot.history, self.spec_ngram, k_row
            )
        return out

    def _verify_worthwhile(self, drafts: Dict[int, List[int]]) -> bool:
        """Should this window verify instead of running the plain path?
        A verify window is ONE device call retiring ``1 + accepted``
        tokens per row; a plain window retires ``sync_steps`` per row per
        call. Compare the EMA-expected verify yield against the plain
        window's certain ``k × active`` — under ``sync_steps == 1`` any
        draft wins (the verify can only add tokens), but at ``k > 1`` a
        lone quoting row must not cost every batchmate ``k - 1`` tokens
        per fetch. Fresh rows (no EMA) count optimistically — the first
        verify measures them."""
        k = self.sync_steps
        if k <= 1:
            return True
        n_active = 0
        expected = 0.0
        for row, slot in enumerate(self.slots):
            if not slot.active:
                continue
            n_active += 1
            d = drafts.get(row)
            if d:
                ema = 1.0 if slot.spec_ema is None else slot.spec_ema
                expected += 1.0 + ema * len(d)
            else:
                expected += 1.0
        return expected >= n_active * k

    def _step_verify(
        self, drafts: Dict[int, List[int]]
    ) -> List[Tuple[int, List[int]]]:
        """One speculative sync window: grow tables for each row's OWN
        horizon (``n_drafts + 1`` writes — exhaustion preempts newest
        rows exactly like a plain window; a preempted row's drafts die
        with its slot), run the verify executable, then drain up to
        ``n_emit`` tokens per row from the fetched planes. The drain is
        the plain window's loop with the window bound per-row instead of
        ``k`` — EOS/budget retirement, block release and preemption
        resume are shared, so every recovery path sees one shape of
        state."""
        K = self.spec_K
        t_w = time.perf_counter()  # ledger window: block growth included
        self._ensure_decode_blocks(
            {row: len(d) + 1 for row, d in drafts.items()}
        )
        if not self.has_active():
            # everything was preempted: same stall attribution as the
            # plain window's early return
            self._journal_window(self.ledger.record_preempt_stall(
                time.perf_counter() - t_w,
                [rid for rid, _ in self._preempted],
            ))
            return []
        d_arr = np.zeros((self.B, K), np.int32)
        nd = np.zeros((self.B,), np.int32)
        for row, d in drafts.items():
            if d and self.slots[row].active:
                d_arr[row, : len(d)] = d
                nd[row] = len(d)
        n_active = sum(1 for s in self.slots if s.active)
        flight.emit(
            "spec_draft", rows=int((nd > 0).sum()), active=n_active,
            drafted=int(nd.sum()),
        )
        flight.emit("sync_window_open", steps=1, active=n_active, spec=1)
        t0 = time.perf_counter()
        (self._cache, self._kv_len, self._last_tok, toks, n_emit, eoss,
         acc, self._active) = self._get("verify_paged", K)(
            self.params, self._cache, self._device_tables(),
            self._kv_len, self._last_tok, self._active, self._rng_keys,
            self._put(jnp.asarray(d_arr)), self._put(jnp.asarray(nd)),
        )
        self.steps += 1
        tok_h = np.asarray(toks)  # [K+1, B] emitted planes
        ne_h = np.asarray(n_emit)  # [B] valid planes per row (m + 1)
        t_fetch = time.perf_counter()
        eos_h = np.asarray(eoss)
        acc_h = np.asarray(acc)  # [B] accepted prefix lengths
        emitted_total = int(ne_h.sum())
        # per-ROW per-token latency, like the plain window's window/k:
        # the mean row advanced emitted_total / n_active tokens in this
        # wall-clock interval
        self._m_itl.observe(
            (t_fetch - t0) * n_active / max(emitted_total, 1)
        )
        self._m_step_device.observe(t_fetch - t0)
        Tmax = self.MB * self.block_size
        done: List[Tuple[int, List[int]]] = []
        deactivate = []
        drafted_total = int(nd.sum())
        accepted_total = 0
        # ledger + per-request spec stats: rid -> (kept, offered, accepted)
        led_rows: Dict[int, Tuple[int, int, int]] = {}
        for i, slot in enumerate(self.slots):
            if not slot.active:
                continue
            offered, m = int(nd[i]), int(acc_h[i])
            accepted_total += m
            if offered:
                self._spec_rids.add(slot.request_id)
            slot.spec_ema = fold_acceptance(slot.spec_ema, offered, m)
            # the exact new frontier (not an upper bound): the device
            # advanced kv_len by exactly n_emit valid positions
            slot.kv_ub = min(slot.kv_ub + int(ne_h[i]), Tmax - 1)
            finished = False
            n_kept = 0
            for j in range(int(ne_h[i])):
                if eos_h[j, i]:
                    finished = True  # EOS token itself is not emitted
                    break
                slot.tokens.append(int(tok_h[j, i]))
                slot.history.append(int(tok_h[j, i]))
                slot.remaining -= 1
                self.stats.decode_tokens += 1
                n_kept += 1
                if slot.remaining <= 0:
                    finished = True  # tokens past the budget discarded
                    break
            led_rows[slot.request_id] = (n_kept, offered, m)
            if finished:
                done.append((slot.request_id, slot.tokens))
                flight.emit(
                    "eos", slot.request_id,
                    reason="budget" if slot.remaining <= 0 else "eos",
                    n_tokens=len(slot.tokens),
                )
                slot.active = False
                deactivate.append(i)
        self.stats.spec_verify_steps += 1
        self.stats.spec_drafted_rows += int((nd > 0).sum())
        self.stats.spec_drafted_tokens += drafted_total
        self.stats.spec_accepted_tokens += accepted_total
        self.stats.spec_emitted_tokens += emitted_total
        flight.emit(
            "spec_verify", drafted=drafted_total, accepted=accepted_total,
            rejected=drafted_total - accepted_total, emitted=emitted_total,
        )
        if deactivate:
            mask = np.ones(self.B, bool)
            mask[deactivate] = False
            self._active = self._active & self._put(jnp.asarray(mask))
            self._retire_rows(deactivate)  # paged: blocks back to the pool
        self._m_step_drain.observe(time.perf_counter() - t_fetch)
        self._journal_window(self.ledger.record_verify(
            time.perf_counter() - t_w, batch=self.B, lanes_per_row=K + 1,
            rows=led_rows,
            ctx_tokens=sum(s.kv_ub for s in self.slots if s.active),
        ))
        self._journal_emitted()
        flight.emit(
            "sync_window_close", steps=1, done=len(done),
            duration_ms=round((time.perf_counter() - t0) * 1e3, 3),
        )
        return done


class ContinuousScheduler:
    """Thread-safe facade: ``submit()`` blocks the caller; a dispatcher
    thread owns the engine, admitting between decode steps.

    Resilience behavior (ISSUE 4):

    - **deadline eviction**: a submit carrying a :class:`Deadline` that
      expires mid-decode has its slot EVICTED within one scheduler
      iteration (``engine.evict_requests``) — the abandoned request stops
      burning a decode slot the moment its client has given up;
    - **reset recovery**: an :class:`EngineStateLost` (the reset wiped every
      slot) RESUBMITS the in-flight prompts once, after a jittered backoff,
      with each request's token budget reduced by what it already emitted
      (the emitted tokens are appended to the resubmitted prompt, so the
      client still receives one seamless continuation). A single transient
      device fault is therefore invisible to callers; a second fault on the
      same request fails it (``rag_inflight_retries_total{outcome}``);
    - **breaker feed**: every reset is reported to the attached
      :class:`~rag_llm_k8s_tpu.resilience.breaker.CircuitBreaker` (set by
      the service) — a reset storm flips readiness, Kubernetes drains the
      pod, and admission sheds with 503 in the meantime.
    """

    def __init__(
        self,
        engine: ContinuousEngine,
        retries: int = 1,
        retry_backoff_s: float = 0.05,
    ):
        self.engine = engine
        self.retries = max(0, retries)
        self.retry_backoff_s = max(0.0, retry_backoff_s)
        # set by the service: engine resets feed the readiness breaker
        self.breaker = None
        # measured busy wall-clock: time the dispatcher spent INSIDE
        # engine.step()/admit_many() — the goodput conservation anchor
        # (per-request attributed chip-seconds must sum to this within
        # tolerance; tests/test_goodput.py pins 5%). Written only by the
        # dispatcher thread; reads are gauge-grade.
        self._busy_s = 0.0
        self._queue: "queue.Queue[Optional[_Pending]]" = queue.Queue()
        self._stop = threading.Event()
        # serializes the stop-check+enqueue in submit() against shutdown()'s
        # final drain — without it an item can land in the queue after the
        # drain and block its caller forever
        self._lifecycle_lock = threading.Lock()
        self.bind_metrics(obs_metrics.default_registry())
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="continuous-scheduler"
        )
        self._worker.start()

    def bind_metrics(self, registry) -> None:
        """Resilience accounting (service rebinds, like the engines)."""
        self._m_resets = registry.counter(
            "rag_engine_resets_total",
            "engine state resets (EngineStateLost / failed decode steps)",
        )
        self._m_retries = registry.labeled_counter(
            "rag_inflight_retries_total",
            "in-flight requests resubmitted after an engine reset "
            "(outcome: resubmitted | succeeded | gave_up)",
        )
        for o in ("resubmitted", "succeeded", "gave_up"):
            self._m_retries.labels(outcome=o)
        dl_fam = registry.labeled_counter(
            "rag_deadline_exceeded_total",
            "requests failed by their end-to-end deadline (stage label)",
        )
        self._m_deadline_queue = dl_fam.labels(stage="queue")
        self._m_deadline_decode = dl_fam.labels(stage="decode")
        self._m_join_timeout = registry.counter(
            "rag_scheduler_join_timeouts_total",
            "scheduler shutdowns whose worker thread outlived join(timeout)",
        )

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: Optional[int] = None,
        seed: Optional[int] = None,  # honored per-row: draws are seed+position keyed
        timeout: Optional[float] = None,
        deadline: Optional[Deadline] = None,
        info: Optional[Dict] = None,  # out-param: per-request engine facts
        tenant: Optional[str] = None,  # edge-interned tenant (bounded set)
        resume_emitted: Optional[Sequence[int]] = None,  # warm restart: prior tokens
    ) -> List[int]:
        if self._stop.is_set():
            raise RuntimeError("scheduler is shut down")
        max_new = (
            self.engine.sampling.max_new_tokens
            if max_new_tokens is None else max_new_tokens
        )
        if max_new <= 0:
            return []
        rid = next(_REQUEST_IDS)  # process-global: flight-journal identity
        if info is not None:
            # out-param: the flight journal keys this request's lifecycle
            # timeline on the id (GET /debug/timeline/<id>)
            info["request_id"] = rid
        item = _Pending(
            request_id=rid, prompt=list(prompt), max_new=max_new, seed=seed,
            deadline=deadline, retries_left=self.retries, tenant=tenant,
        )
        # the replay trace record (sim/replay.py): everything a re-drive
        # needs to reproduce this request — the prompt token ids ride
        # along only while the arrival_ids knob is on (they dominate the
        # ring's memory at long prompts)
        arr = {"prompt_len": len(item.prompt), "max_new": max_new}
        if seed is not None:
            arr["seed"] = seed
        if deadline is not None:
            arr["deadline_ms"] = deadline.budget_ms
        if tenant is not None:
            # rides the trace record too: a re-driven journal re-prices
            # per tenant (sim/replay.py forwards it into its submits)
            arr["tenant"] = tenant
            self.engine.ledger.note_tenant(rid, tenant)
        if flight.arrival_ids():
            arr["ids"] = list(item.prompt)
        flight.emit("arrival", rid, **arr)
        if resume_emitted:
            # warm restart (server/main.py): tokens a dead incarnation's
            # WAL proved emitted fold in through the SAME path a preempt
            # resume uses — the prompt grows, the budget shrinks, and the
            # delivered stream stays byte-identical to an uninterrupted
            # run. The arrival above recorded the ORIGINAL prompt; the
            # token_emit re-journals the folded tokens into THIS
            # incarnation's WAL so a second crash still reconstructs the
            # full stream from one epoch.
            self._fold_emitted(item, list(resume_emitted))
            if item.emitted:
                item.resumed = True
                flight.emit("token_emit", rid, toks=list(item.emitted))
            flight.emit(
                "resubmit", rid, outcome="restored",
                n_emitted=len(item.emitted),
            )
        with self._lifecycle_lock:  # stop-check + enqueue must be atomic
            if self._stop.is_set():
                raise RuntimeError("scheduler is shut down")
            self._queue.put(item)
        wait_t = timeout
        if wait_t is None and deadline is not None:
            # small grace past the deadline: the worker evicts the row and
            # delivers a stage-precise error within one iteration — prefer
            # that over racing it with a caller-side raise
            wait_t = deadline.wait_timeout() + 0.25
        if not item.done.wait(wait_t):
            if deadline is not None and deadline.expired():
                # the worker's eviction sweep frees the slot; the caller
                # need not (and must not) block on it. Mark the item so the
                # sweep skips ITS deadline-counter increment — this expiry
                # is counted once, at the caller's stage="generate"
                item.abandoned = True
                raise DeadlineExceeded("generate", deadline.budget_ms)
            raise TimeoutError("generation timed out")
        if item.error is not None:
            raise item.error
        if info is not None and item.blocks_allocated is not None:
            # paged mode: the row's peak block footprint (per-row
            # blocks_allocated in the /generate timings block)
            info["kv_blocks_allocated"] = item.blocks_allocated
        if info is not None and item.goodput is not None:
            # goodput ledger: this request's attributed chip-time figures
            # (chip_ms / goodput_frac / cost_usd / speculation stats) —
            # the service folds them into the /generate timings block
            info["goodput"] = item.goodput
        if info is not None and item.spec_seen:
            # approximation fingerprint (obs/shadow.py): verify windows
            # judged drafts for this request — stamped from ENGINE state
            # (pop_spec_seen), never the goodput ledger, so
            # TPU_RAG_GOODPUT=0 cannot erase speculation attribution
            # from shadow audits
            ap = info.setdefault("approx", [])
            if "spec_verify" not in ap:
                ap.append("spec_verify")
        if info is not None and item.migrate is not None:
            # prefill-role hand-off (disaggregated pools): the returned
            # tokens are only the admission token — the caller (the
            # router) forwards this packet to a decode-role replica's
            # ``submit_migrated``, which finishes the stream
            info["migrate_packet"] = item.migrate
        return item.result

    def submit_migrated(
        self,
        packet: Dict,
        timeout: Optional[float] = None,
        deadline: Optional[Deadline] = None,
        info: Optional[Dict] = None,
        tenant: Optional[str] = None,
    ) -> List[int]:
        """Land a prefill-role peer's migration packet on THIS scheduler's
        engine and block until the stream completes. The request keeps
        its process-global id, so the flight journal shows ONE lifecycle
        across both engines (arrival/admit/migrate_begin on the prefill
        side; migrate_done/complete here). Returns the FULL stream — the
        packet's admission token plus everything decoded here —
        byte-identical to a unified run by the (seed, position) fold."""
        if self._stop.is_set():
            raise RuntimeError("scheduler is shut down")
        rid = packet["request_id"]
        tenant = tenant if tenant is not None else packet.get("tenant")
        item = _Pending(
            request_id=rid,
            # a decode-side reset re-prefills prompt+emitted from these
            # — the same fold path as any reset recovery
            prompt=list(packet.get("prompt", ())),
            max_new=packet["remaining"] + len(packet["tokens"]),
            seed=packet.get("seed"),
            deadline=deadline, retries_left=self.retries, tenant=tenant,
            migrate=packet,
        )
        if info is not None:
            info["request_id"] = rid
        if tenant is not None:
            self.engine.ledger.note_tenant(rid, tenant)
        with self._lifecycle_lock:  # stop-check + enqueue must be atomic
            if self._stop.is_set():
                raise RuntimeError("scheduler is shut down")
            self._queue.put(item)
        wait_t = timeout
        if wait_t is None and deadline is not None:
            wait_t = deadline.wait_timeout() + 0.25
        if not item.done.wait(wait_t):
            if deadline is not None and deadline.expired():
                item.abandoned = True
                raise DeadlineExceeded("generate", deadline.budget_ms)
            raise TimeoutError("generation timed out")
        if item.error is not None:
            raise item.error
        if info is not None and item.blocks_allocated is not None:
            info["kv_blocks_allocated"] = item.blocks_allocated
        if info is not None and item.goodput is not None:
            info["goodput"] = item.goodput
        return item.result

    def busy_seconds(self) -> float:
        """Wall-clock the dispatcher spent inside engine device work
        (step + admissions) — the independent measurement the goodput
        conservation invariant is checked against."""
        return self._busy_s

    def run_on_engine(self, fn) -> bool:
        """Enqueue a host-side engine task — ``fn(engine)`` — executed by
        the dispatcher thread between admissions and steps. The engine is
        single-owner (its step executables DONATE the device state), so
        this is the only safe way for another thread (the lookahead
        executor's KV pre-staging, rag/lookahead.py) to touch it. Fire and
        forget; a task failure is contained exactly like a step failure
        (EngineStateLost recovery resubmits the in-flight requests).
        Returns False when the scheduler is shutting down."""
        if not callable(fn):
            raise TypeError("run_on_engine expects a callable(engine)")
        with self._lifecycle_lock:
            if self._stop.is_set():
                return False
            self._queue.put(fn)
        return True

    def shutdown(self):
        from rag_llm_k8s_tpu.engine.batching import _join_worker

        self._stop.set()
        with self._lifecycle_lock:
            self._queue.put(None)
        _join_worker(self._worker, self._m_join_timeout, "continuous-scheduler")
        # the worker's own drain ran before join returned; under the lock no
        # new item can have been enqueued since — sweep anything that raced
        # in between the worker's drain and _stop becoming visible
        with self._lifecycle_lock:
            while True:
                try:
                    it = self._queue.get_nowait()
                except queue.Empty:
                    break
                if it is not None and not callable(it):
                    it.error = RuntimeError("scheduler is shut down")
                    it.done.set()

    # ------------------------------------------------------------------
    def _run(self):
        waiting: Dict[int, _Pending] = {}
        item: Optional[_Pending] = None
        try:
            item = self._run_loop(waiting)
        finally:
            # the worker is exiting for WHATEVER reason (shutdown() or an
            # unguarded exception): close the door FIRST so post-mortem
            # submits fail fast instead of enqueueing into a drained queue
            # and blocking their caller forever
            self._stop.set()
            # fail everything still in flight or queued so no caller blocks
            # forever on a scheduler that has stopped (answer() submits with
            # timeout=None)
            err = RuntimeError("scheduler is shut down")
            leftovers = list(waiting.values())
            waiting.clear()
            if item is not None:
                leftovers.append(item)
            with self._lifecycle_lock:  # no submit can race this drain
                while True:
                    try:
                        queued = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if queued is not None and not callable(queued):
                        leftovers.append(queued)
            for it in leftovers:
                self.engine.discard_request_goodput(it.request_id)
                it.error = err
                it.done.set()

    def _run_loop(self, waiting: Dict[int, "_Pending"]) -> Optional["_Pending"]:
        """Returns the un-acked in-hand item (if any) when stopping."""
        eng = self.engine
        while not self._stop.is_set():
            # deadline sweep once per iteration: an expired in-flight request
            # frees its decode slot within ONE scheduler step
            self._evict_expired(waiting)
            if eng.has_active():
                # decode never waits on arrivals: peek, admit, step
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    item = None
            else:
                item = self._queue.get()  # idle: block until work arrives
            while item is not None:  # admit everything that fits right now
                if self._stop.is_set():
                    return item if not callable(item) else None
                if callable(item):
                    # engine task (lookahead pre-staging): host+one small
                    # device call, run in arrival order between admissions
                    self._run_engine_task(item, waiting)
                    item = self._next_nowait()
                    continue
                if self._expire_queued(item):
                    # expired while queued: fail fast, never admit — under
                    # overload this is what keeps dead work off the device
                    item = self._next_nowait()
                    continue
                if item.migrate is not None:
                    # a prefill-role peer's migration packet: lands via
                    # its own import path (no prefill, no bucketing)
                    leftover = self._admit_migrated(item, waiting)
                    if leftover is not None:
                        return leftover
                    item = self._next_nowait()
                    continue
                # paged backpressure: a pool that can't take this prompt NOW
                # keeps it QUEUED (decode frees blocks every window; the
                # growing queue is what trips the PR-4 admission gate's 429s
                # upstream) — only a prompt the whole pool couldn't hold
                # fails outright
                state = eng.admission_state(len(item.prompt))
                if state == "never":
                    item.error = PoolExhausted(
                        eng.blocks_needed(len(item.prompt)),
                        eng.kv_pool.usable_blocks() if eng.kv_pool else 0,
                    )
                    item.done.set()
                    item = self._next_nowait()
                    continue
                if state == "wait":
                    self._safe_step(waiting)
                    self._evict_expired(waiting)
                    continue
                free = eng.free_slots()
                if not free:
                    # no room: decode until a slot frees, then admit
                    self._safe_step(waiting)
                    self._evict_expired(waiting)
                    continue
                # GROUP admission: drain whatever else is already queued up
                # to the free-slot count — the engine batches same-bucket
                # prefills and fetches all first tokens in one round-trip
                batch = [item]
                while len(batch) < len(free):
                    try:
                        nxt = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is None:
                        break
                    if callable(nxt):
                        self._run_engine_task(nxt, waiting)
                        continue
                    if self._expire_queued(nxt):
                        continue  # dead on arrival: no prefill for it
                    if nxt.migrate is not None:
                        # migrated packets never batch with prefills:
                        # requeue and stop draining (a bare put-back
                        # here would re-pull it in this very loop)
                        self._queue.put(nxt)
                        break
                    batch.append(nxt)
                try:
                    t_busy = time.perf_counter()
                    try:
                        admitted = eng.admit_many(
                            [(b.request_id, b.prompt, b.max_new, b.seed) for b in batch]
                        )
                    finally:
                        self._busy_s += time.perf_counter() - t_busy
                    for b, res in zip(batch, admitted):
                        if isinstance(res, PoolExhausted):
                            # the chunk raced the pool (another chunk of
                            # this very group took the blocks): requeue —
                            # this is backpressure, not a failure
                            self._queue.put(b)
                            continue
                        if isinstance(res, BaseException):
                            # per-chunk failure: only ITS items fail; other
                            # chunks' admissions stand and keep decoding
                            b.error = res
                            b.done.set()
                            continue
                        _, finished = res
                        # the first token exists the moment admission
                        # returns (sampled at prefill): submit → here IS
                        # the request's exact TTFT, queue wait included.
                        # A resubmitted request already observed its real
                        # TTFT on the first attempt — a second sample would
                        # double-count it and fold the reset backoff into
                        # the histogram the SLO layer alerts on (same for a
                        # pool-preemption resume)
                        chunk_rec = eng._chunk_admissions.get(b.request_id)
                        if chunk_rec is not None:
                            # interleaved admission: no first token yet —
                            # hand the engine the real submit stamp so the
                            # mixed window that samples tok0 observes the
                            # exact TTFT (None keeps the retry/resume
                            # no-double-count rule above)
                            chunk_rec["t_submit"] = (
                                b.t_submit
                                if not b.retried and not b.resumed else None
                            )
                        elif not b.retried and not b.resumed:
                            eng._m_ttft.observe(time.monotonic() - b.t_submit)
                        if finished is not None:
                            self._deliver(b, finished)
                        elif eng.pool_role == "prefill":
                            # disaggregated hand-off: the request leaves
                            # this engine as a packet; export failure
                            # keeps it decoding locally (role is policy)
                            self._export_or_keep(b, waiting)
                        else:
                            waiting[b.request_id] = b
                except EngineStateLost as e:
                    # the reset (inside the engine) wiped every slot: recover
                    # by resubmitting this batch AND the in-flight requests —
                    # their emitted tokens were lost with the slots, so they
                    # restart from their original prompts
                    self._handle_reset(e, waiting, extra=batch, emitted={})
                except BaseException as e:  # noqa: BLE001 — deliver to waiters
                    for b in batch:
                        b.error = e
                        b.done.set()
                item = self._next_nowait()
            if eng.has_active():
                self._safe_step(waiting)
        return None

    def _next_nowait(self) -> Optional["_Pending"]:
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            return None

    def _evict_expired(self, waiting: Dict[int, "_Pending"]):
        """Evict in-flight requests whose deadline has passed: free their
        device slots and deliver the stage-precise error."""
        expired = [
            rid for rid, it in waiting.items()
            if it.deadline is not None and it.deadline.expired()
        ]
        if not expired:
            return
        self.engine.evict_requests(expired)
        for rid in expired:
            it = waiting.pop(rid)
            if not it.abandoned:  # the caller already counted its expiry
                self._m_deadline_decode.inc()
            self.engine.discard_request_goodput(rid)  # never delivered
            it.error = DeadlineExceeded("decode", it.deadline.budget_ms)
            it.done.set()

    def _expire_queued(self, item: "_Pending") -> bool:
        """Fail an expired item straight out of the queue (stage=queue) —
        dead work must never reach the device. True when it was expired."""
        if item.deadline is None or not item.deadline.expired():
            return False
        if not item.abandoned:
            self._m_deadline_queue.inc()
        item.error = DeadlineExceeded("queue", item.deadline.budget_ms)
        item.done.set()
        return True

    def _deliver(self, item: "_Pending", tokens: List[int]):
        """Complete one request: tokens emitted before a recovered reset
        (if any) prepend the continuation — the client sees one stream."""
        if item.retried:
            self._m_retries.labels(outcome="succeeded").inc()
        item.blocks_allocated = self.engine.pop_blocks_allocated(item.request_id)
        item.result = item.emitted + tokens
        item.goodput = self.engine.pop_request_goodput(
            item.request_id, tokens=len(item.result)
        )
        pop_spec = getattr(self.engine, "pop_spec_seen", None)
        item.spec_seen = bool(pop_spec(item.request_id)) if pop_spec else False
        # stream_fnv anchors the timeline to the BYTES the client received:
        # a reconstructed lifecycle (admit → reset → resubmit → complete)
        # is provably consistent with the delivered stream. The goodput
        # attribution rides along so an offline journal can compute
        # cost-per-query percentiles with no live pod; the tenant stamp is
        # what lets obs/tenants.py price the journal per tenant.
        extra = {}
        if item.goodput is not None:
            extra["chip_ms"] = item.goodput["chip_ms"]
            if "cost_usd" in item.goodput:
                extra["cost_usd"] = round(item.goodput["cost_usd"], 8)
        if item.tenant is not None:
            extra["tenant"] = item.tenant
        flight.emit(
            "complete", item.request_id, n_tokens=len(item.result),
            stream_fnv=flight.stream_hash(item.result), **extra,
        )
        item.done.set()

    def _export_or_keep(self, item: "_Pending", waiting) -> None:
        """Prefill-role hand-off: pull the freshly admitted request off
        the engine as a migration packet and deliver it to the submitter
        (the router forwards it to a decode-role replica). Any failure
        keeps the request decoding LOCALLY — a broken hand-off degrades
        to unified service instead of failing the request. No
        ``complete`` event fires here: the decode-role engine that
        imports the packet finishes the stream and emits it."""
        eng = self.engine
        packet = None
        try:
            t_busy = time.perf_counter()
            try:
                packet = eng.export_request(item.request_id)
            finally:
                self._busy_s += time.perf_counter() - t_busy
        except BaseException:  # noqa: BLE001 — nothing donated; state intact
            logger.exception(
                "migration export failed; serving request %d locally",
                item.request_id,
            )
        if packet is None:
            waiting[item.request_id] = item
            return
        # the packet needs what only the scheduler knows: the original
        # prompt and seed — a decode-side reset re-prefills from them
        packet["prompt"] = list(item.prompt)
        packet["seed"] = item.seed
        packet["tenant"] = item.tenant
        item.blocks_allocated = eng.pop_blocks_allocated(item.request_id)
        item.goodput = eng.pop_request_goodput(
            item.request_id, tokens=len(packet["tokens"])
        )
        item.migrate = packet
        item.result = list(packet["tokens"])
        item.done.set()

    def _admit_migrated(
        self, item: "_Pending", waiting
    ) -> Optional["_Pending"]:
        """Land a migration packet on the engine, with the same
        backpressure discipline as admission: while the pool or the slot
        map can't take it NOW, decode windows run (they retire rows and
        free blocks every iteration) and the import retries. Only a
        packet the whole pool could never hold fails outright. Returns
        the item when interrupted by shutdown (the caller's drain fails
        it); None otherwise."""
        eng = self.engine
        pkt = item.migrate
        need = pkt["n_blocks"]
        while not self._stop.is_set():
            usable = eng.kv_pool.usable_blocks() if eng.kv_pool else 0
            if not eng.paged or need > usable:
                item.error = PoolExhausted(need, usable)
                item.done.set()
                return None
            if self._expire_queued(item):
                return None
            if (not eng.free_slots()
                    or not eng.kv_pool.can_alloc(need)) and eng.has_active():
                self._safe_step(waiting)
                self._evict_expired(waiting)
                continue
            try:
                t_busy = time.perf_counter()
                try:
                    eng.import_request(pkt)
                finally:
                    self._busy_s += time.perf_counter() - t_busy
            except PoolExhausted as e:
                if eng.has_active():
                    # blocks free as decode retires rows — try again
                    self._safe_step(waiting)
                    self._evict_expired(waiting)
                    continue
                item.error = e
                item.done.set()
                return None
            except EngineStateLost as e:
                # the donated import died and the engine reset: this
                # item re-enters as a plain resubmission — prompt + the
                # tokens the prefill side already emitted re-prefill
                # HERE through the fold path, streams byte-identical
                item.migrate = None
                self._handle_reset(
                    e, waiting, extra=[item],
                    emitted={item.request_id: list(pkt["tokens"])},
                )
                return None
            except BaseException as e:  # noqa: BLE001
                item.error = e
                item.done.set()
                return None
            item.migrate = None  # imported: a later reset resubmits by prompt
            waiting[item.request_id] = item
            return None
        return item  # stopping mid-wait: hand back like the admit loop

    def _fold_emitted(self, it: "_Pending", toks: List[int]) -> None:
        """Fold already-emitted tokens into a request about to resubmit:
        resume only when prompt+emitted still fits a slot — past the
        largest bucket admit_many would silently left-truncate the context
        and the "seamless continuation" would be conditioned on a different
        prompt; restarting from scratch is exact. Shared by reset recovery
        and pool-preemption resume."""
        if sim_policy.resume_fits(len(it.prompt), len(toks),
                                  max(self.engine.buckets)):
            it.emitted.extend(toks)
            it.prompt = list(it.prompt) + toks
            it.max_new = max(1, it.max_new - len(toks))

    def _resume_preempted(self, waiting: Dict[int, "_Pending"]):
        """Requeue requests the paged engine preempted on pool exhaustion:
        prompt + emitted resubmits (greedy streams provably identical), the
        budget shrinks by what was already produced. Unlike reset recovery
        this burns no retry — preemption is scheduled backpressure, not a
        fault — and the TTFT histogram is not re-fed."""
        for rid, toks in self.engine.drain_preempted():
            it = waiting.pop(rid, None)
            if it is None:
                continue
            self._fold_emitted(it, toks)
            it.resumed = True
            # the resumed admission re-feeds prompt+emitted — tokens the
            # chip already computed once: attribute that admission's lanes
            # to preempt_rework (the ledger's goodput cost of preemption)
            self.engine.mark_rework(rid)
            flight.emit(
                "resubmit", rid, outcome="preempt_resume",
                n_emitted=len(toks),
            )
            self._queue.put(it)

    def _handle_reset(self, cause, waiting, extra, emitted):
        """After an engine reset: resubmit what can still be served, fail
        the rest. ``emitted`` maps request_id → tokens produced before the
        reset (captured from the host slots when the failure site allows);
        resubmitted prompts carry them so decode resumes where it stopped
        and the budget shrinks by what was already produced."""
        self._m_resets.inc()
        if self.breaker is not None:
            self.breaker.record_reset()
        items = list(waiting.values()) + list(extra)
        waiting.clear()
        retry = []
        for it in items:
            expired = it.deadline is not None and it.deadline.expired()
            if it.retries_left > 0 and not expired and not self._stop.is_set():
                retry.append(it)
            else:
                self._m_retries.labels(outcome="gave_up").inc()
                flight.emit("resubmit", it.request_id, outcome="gave_up")
                self.engine.discard_request_goodput(it.request_id)
                it.error = cause
                it.done.set()
        if not retry:
            return
        logger.warning(
            "engine reset (%s); resubmitting %d in-flight request(s)",
            cause, len(retry),
        )
        if self.retry_backoff_s > 0:
            # jittered: a device that just faulted gets a beat before the
            # retries' prefills land on it again
            time.sleep(random.uniform(0.5, 1.0) * self.retry_backoff_s)
        for it in retry:
            toks = emitted.get(it.request_id, [])
            self._fold_emitted(it, toks)
            it.retries_left -= 1
            it.retried = True
            # reset recovery re-prefills the whole prompt (+ emitted):
            # rework lanes, not fresh prefill, in the goodput ledger
            self.engine.mark_rework(it.request_id)
            self._m_retries.labels(outcome="resubmitted").inc()
            flight.emit(
                "resubmit", it.request_id, outcome="resubmitted",
                n_emitted=len(toks),
            )
            self._queue.put(it)

    def _run_engine_task(self, task, waiting: Dict[int, "_Pending"]):
        """Execute one enqueued engine task with step-grade containment: a
        task that invalidates the donated device state (EngineStateLost
        from a failed prestage scatter) recovers exactly like a failed
        step — reset already happened inside the engine, the in-flight
        requests resubmit from their prompts."""
        try:
            task(self.engine)
        except EngineStateLost as e:
            # the engine reset itself before raising: slots (and any
            # emitted tokens) are gone — resubmit from the prompts
            logger.exception(
                "engine task reset the engine; recovering %d in-flight "
                "request(s)", len(waiting),
            )
            self._handle_reset(e, waiting, extra=[], emitted={})
        except BaseException:  # noqa: BLE001 — tasks must never kill the loop
            logger.exception("engine task failed (engine state intact)")

    def _safe_step(self, waiting: Dict[int, "_Pending"]):
        """One decode step that can never kill the dispatcher: a device
        error resets the slots and RESUBMITS the in-flight requests (once
        each) so a transient fault stays invisible to callers; requests out
        of retries (or past deadline) get the error instead of a hang."""
        try:
            t_busy = time.perf_counter()
            try:
                done = self.engine.step()
            finally:
                self._busy_s += time.perf_counter() - t_busy
            self._drain_done(done, waiting)
            self._resume_preempted(waiting)
        except BaseException as e:  # noqa: BLE001 — recover, don't die
            logger.exception(
                "decode step failed; recovering %d in-flight request(s)",
                len(waiting),
            )
            # capture what each in-flight request already produced BEFORE
            # reset() wipes the host slots — the resubmission resumes from
            # the original prompt + these tokens
            emitted = {
                s.request_id: list(s.tokens)
                for s in self.engine.slots if s.active
            }
            try:
                self.engine.reset()
            except BaseException:  # noqa: BLE001 — a failed reset must not kill the loop
                logger.exception("engine reset failed after step failure")
            self._handle_reset(e, waiting, extra=[], emitted=emitted)

    def _drain_done(self, done, waiting):
        for rid, tokens in done:
            item = waiting.pop(rid, None)
            if item is not None:
                self._deliver(item, tokens)


@dataclass
class _Pending:
    request_id: int
    prompt: List[int]
    max_new: int
    seed: Optional[int] = None
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[List[int]] = None
    error: Optional[BaseException] = None
    t_submit: float = field(default_factory=time.monotonic)  # TTFT anchor
    deadline: Optional[Deadline] = None
    retries_left: int = 0  # reset-recovery resubmissions remaining
    retried: bool = False  # ever resubmitted (success/failure accounting)
    emitted: List[int] = field(default_factory=list)  # pre-reset tokens
    abandoned: bool = False  # caller gave up (it counted the expiry)
    resumed: bool = False  # requeued after a paged pool preemption
    blocks_allocated: Optional[int] = None  # paged: peak block footprint
    goodput: Optional[Dict] = None  # ledger attribution (chip_ms/cost/spec)
    spec_seen: bool = False  # verify windows judged drafts for this request
    tenant: Optional[str] = None  # edge-interned tenant (complete stamp)
    migrate: Optional[Dict] = None  # disagg: migration packet (in or out)
