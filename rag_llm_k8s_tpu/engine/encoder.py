"""Batched embedding runner over the Flax bge-m3 encoder.

The reference embeds ONE chunk per ``SentenceTransformer.encode`` call in a
Python loop (/root/reference/llm/rag.py:55,101,133). Here ingest batches whole
chunk sets into bucketed device calls (BASELINE.json config #2: the
"batch embedding (PDF-chunk ingest path)") — right-padded, mask-aware, one
executable per (batch, length) bucket.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from rag_llm_k8s_tpu.core.config import DTypePolicy, EncoderConfig
from rag_llm_k8s_tpu.core.mesh import MeshContext
from rag_llm_k8s_tpu.models.bge_m3 import BgeM3Encoder
from rag_llm_k8s_tpu.resilience import faults
from rag_llm_k8s_tpu.utils.buckets import bucket_len, next_pow2
from rag_llm_k8s_tpu.utils.tokens import truncate_keep_eos


class EncoderRunner:
    def __init__(
        self,
        config: EncoderConfig,
        params,
        dtypes: DTypePolicy = DTypePolicy(),
        mesh: Optional[MeshContext] = None,
        # 1536/3072 snug buckets: the reference's 1000-word chunks tokenize
        # to ~1.3-1.5k pieces — padding them to 2048 wastes a third of every
        # (compute-bound) ingest forward
        length_buckets: Sequence[int] = (
            64, 128, 256, 512, 1024, 1536, 2048, 3072, 4096, 8192
        ),
        max_batch: int = 32,
        eos_id: Optional[int] = None,
    ):
        self.config = config
        self.params = params
        self.dtypes = dtypes
        self.mesh = mesh
        # when set, sequences clamped to the largest bucket keep a trailing
        # EOS — bge-m3's CLS pooling is trained on </s>-terminated input
        self.eos_id = eos_id
        self.length_buckets = tuple(
            b for b in length_buckets if b <= config.max_encode_len
        ) or (config.max_encode_len,)
        self.max_batch = max_batch
        self.model = BgeM3Encoder(config, dtypes)
        self._jit = jax.jit(
            lambda params, tokens, mask: self.model.apply(
                {"params": params}, tokens, mask
            )
        )

    def prepare_batch(self, ids: Sequence[int]):
        """One bucketed, padded, EOS-preserving ``[1, S]`` (tokens, mask)
        pair — the SAME truncation/bucketing rules the ingest path applies,
        shared with the server's fused query-retrieval so query and chunk
        embeddings can never diverge."""
        S = bucket_len(max(len(ids), 1), self.length_buckets)
        ids = truncate_keep_eos(ids, S, self.eos_id)
        tokens = np.full((1, S), self.config.pad_token_id, np.int32)
        mask = np.zeros((1, S), np.int32)
        tokens[0, : len(ids)] = ids
        mask[0, : len(ids)] = 1
        return tokens, mask

    def encode(self, token_lists: Sequence[Sequence[int]]) -> np.ndarray:
        """Token-id sequences → ``[N, hidden]`` fp32 unit vectors.

        Two-phase: DISPATCH every bucketed group back-to-back (JAX dispatch
        is async, so the device pipeline stays full and the host pads the
        next group while the previous one computes), then fetch ALL results
        in one device→host transfer. One fetch per call instead of one per
        ``max_batch`` group — on a slow host link the per-group fetch was
        ~40% of warm ingest time (round-4: ~13 ms of every chunk's 49 ms).
        """
        if not token_lists:
            return np.zeros((0, self.config.hidden_size), np.float32)
        faults.maybe_fail("embed")
        out = np.zeros((len(token_lists), self.config.hidden_size), np.float32)
        # group by length bucket to minimize padding waste
        order = sorted(range(len(token_lists)), key=lambda i: len(token_lists[i]))
        pending = []  # (group, device_emb)
        pad = self.config.pad_token_id
        for start in range(0, len(order), self.max_batch):
            group = order[start : start + self.max_batch]
            S = bucket_len(max(len(token_lists[i]) for i in group), self.length_buckets)
            B = next_pow2(len(group))
            tokens = np.full((B, S), pad, np.int32)
            mask = np.zeros((B, S), np.int32)
            for row, i in enumerate(group):
                ids = truncate_keep_eos(token_lists[i], S, self.eos_id)
                tokens[row, : len(ids)] = ids
                mask[row, : len(ids)] = 1
            pending.append(
                (group, self._jit(self.params, jnp.asarray(tokens), jnp.asarray(mask)))
            )
        # device-side concat → ONE host fetch for the whole call (group
        # batch dims differ, but the hidden dim is shared)
        stacked = np.asarray(jnp.concatenate([e for _, e in pending], axis=0))
        off = 0
        for group, e in pending:
            for row, i in enumerate(group):
                out[i] = stacked[off + row]
            off += e.shape[0]
        return out
