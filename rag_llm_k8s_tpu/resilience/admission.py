"""Bounded admission control + load shedding for the serving path.

The seed admitted every request unconditionally: concurrent ``/generate``
calls piled threads onto an unbounded ``queue.Queue`` behind the scheduler,
so a burst beyond the device's throughput grew the queue (and every queued
request's latency) without bound — the classic metastable overload shape.
The gate in front of the pipeline makes overload a *fast, explicit* signal
instead:

- up to ``max_concurrency`` requests run concurrently;
- up to ``max_queue`` more wait (bounded, deadline-aware);
- everything beyond that is REJECTED immediately with a machine-readable
  reason and a ``Retry-After`` hint — a 429 the client's retry loop can
  honor costs microseconds; a queued request that times out after 120 s
  costs a thread, a socket, and a user.

The gate also fronts the circuit breaker: while the breaker is open the pod
is draining, so new work is shed with 503 + ``Retry-After`` equal to the
breaker's estimated close time.

``rag_admission_rejected_total{reason, tenant}`` counts every shed
request; the live ``waiting`` count folds into
``rag_admission_queue_depth``.

Tenant-aware fair share (ISSUE 20): when the queue is FULL, an arriving
tenant under its fair share of the gate (capacity / tenants present) may
displace the newest queued waiter of a tenant OVER its share — that
waiter sheds with reason="fair_share" and the newcomer takes its place.
One tenant's burst can no longer monopolize the whole queue; tenants
below their share still get queued even at saturation. Tenant values
arrive pre-interned through the edge's TenantTracker (tracked or
``__other__``), so every per-tenant structure here is cardinality-bounded
by construction. Requests with no tenant never displace and are never
displaced — tenancy off keeps the exact pre-fair-share behavior.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, List, Optional

from rag_llm_k8s_tpu.obs import flight
from rag_llm_k8s_tpu.resilience.breaker import CircuitBreaker
from rag_llm_k8s_tpu.resilience.deadline import Deadline, DeadlineExceeded

__all__ = ["AdmissionController", "AdmissionRejected"]


class AdmissionRejected(RuntimeError):
    """Load shed at the gate. ``status`` is the HTTP code the edge maps it
    to (429 = over capacity, retry; 503 = draining/breaker, go elsewhere)."""

    def __init__(self, reason: str, status: int, retry_after_s: float):
        super().__init__(f"admission rejected: {reason}")
        self.reason = reason
        self.status = status
        self.retry_after_s = retry_after_s


class AdmissionController:
    def __init__(
        self,
        max_concurrency: int = 16,
        max_queue: int = 64,
        retry_after_s: float = 1.0,
        breaker: Optional[CircuitBreaker] = None,
    ):
        if max_concurrency < 1:
            raise ValueError(f"max_concurrency={max_concurrency}: expected >= 1")
        if max_queue < 0:
            raise ValueError(f"max_queue={max_queue}: expected >= 0")
        self.max_concurrency = max_concurrency
        self.max_queue = max_queue
        self.retry_after_s = retry_after_s
        self.breaker = breaker
        self._cv = threading.Condition()
        self.active = 0
        self.waiting = 0
        # lifecycle drain (resilience/lifecycle.py): once set, EVERY new
        # or queued request is shed with 503 reason="draining" while the
        # already-admitted ones run to completion — the gate is how a
        # rolling replica stops taking work without dropping work
        self._draining = False
        self._drain_retry_after_s = retry_after_s
        # fair-share state (all under _cv): in-gate count per tenant
        # (active + waiting) and one record per queued waiter, queue
        # order — the displacement victim search walks it newest-first.
        # Bounded: tenants arrive interned (top-K + "__other__"), waiters
        # by max_queue.
        self._tenant_gate: Dict[str, int] = {}
        self._waiters: List[dict] = []
        # set by the service (obs wiring): labeled-counter families for
        # rag_admission_rejected_total / rag_deadline_exceeded_total —
        # None keeps the gate standalone
        self.reject_counter = None
        self.deadline_counter = None
        # set by the service (tenant attribution): the labeled-counter
        # family for rag_tenant_sheds_total — per-tenant shed counts, the
        # data a fair-share gate (ROADMAP item 1) acts on. Label values
        # arrive pre-interned through the edge's TenantTracker, so the
        # family stays cardinality-bounded by construction.
        self.tenant_shed_counter = None
        # set by the service when the engine serves from a paged KV pool
        # (engine/kv_pool.py): a callable returning True while the pool has
        # ZERO free blocks. While saturated, a request that would have to
        # WAIT is shed immediately with 429 reason="pool_exhausted" —
        # queueing behind a pool that cannot grow only converts the
        # client's retry budget into server-side latency. Requests under
        # the concurrency cap still run: decode frees blocks every window,
        # and the scheduler's own backpressure orders them correctly.
        self.saturation_hint = None
        # hotness-aware refinement of the saturation shed (KV tiering): a
        # callable returning the RECLAIMABLE block count — registered
        # prefix blocks in a non-hot tier, which the scheduler's next
        # admission sweep returns to the pool without touching a live row.
        # While that is positive, a saturated pool is cache warmth, not
        # true pressure: the request QUEUES (bounded, deadline-aware)
        # instead of shedding. Tier occupancy, not raw headroom, decides.
        self.reclaimable_hint = None
        # set by the service (obs/flight.py): called with an incident
        # trigger name when a shed is post-mortem-worthy — today only
        # pool-exhaustion sheds, which mean HBM pressure, not tuning
        self.incident_hook = None

    # -- internals -------------------------------------------------------
    def _reject(self, reason: str, status: int, retry_after_s: float,
                tenant: Optional[str] = None):
        fam = self.reject_counter
        if fam is not None:
            # tenant label values are pre-interned at the edge (tracked
            # or "__other__"), so the series count stays bounded at
            # reasons x (top-K + 1) even under adversarial tenant ids
            fam.labels(reason=reason, tenant=tenant or "__other__").inc()
        if tenant is not None:
            tfam = self.tenant_shed_counter
            if tfam is not None:
                tfam.labels(tenant=tenant).inc()
        flight.emit("shed", reason=reason, status=status,
                    **({"tenant": tenant} if tenant else {}))
        if reason == "pool_exhausted" and self.incident_hook is not None:
            try:
                self.incident_hook("pool_exhausted_shed")
            except Exception:  # noqa: BLE001 — capture must not break the shed
                pass
        raise AdmissionRejected(reason, status, retry_after_s)

    def _acquire(self, deadline: Optional[Deadline],
                 tenant: Optional[str] = None) -> None:
        if self._draining:
            self._reject("draining", 503, self._drain_retry_after_s,
                         tenant=tenant)
        breaker = self.breaker
        if breaker is not None and breaker.open:
            # draining: shed EVERYTHING, even below the concurrency cap —
            # the whole point is to stop feeding a sick device
            self._reject(
                "breaker_open", 503,
                max(breaker.retry_after_s(), self.retry_after_s),
                tenant=tenant,
            )
        with self._cv:
            if tenant is not None:
                self._tenant_gate[tenant] = (
                    self._tenant_gate.get(tenant, 0) + 1
                )
            try:
                self._acquire_locked(deadline, tenant)
            except BaseException:
                # every rejection path gives the in-gate count back; a
                # SUCCESSFUL acquire keeps it until _release(tenant)
                self._gate_dec_locked(tenant)
                raise

    def _gate_dec_locked(self, tenant: Optional[str]) -> None:
        if tenant is None:
            return
        c = self._tenant_gate.get(tenant, 0) - 1
        if c <= 0:
            self._tenant_gate.pop(tenant, None)
        else:
            self._tenant_gate[tenant] = c

    def _fair_share_victim(self, tenant: Optional[str]) -> Optional[dict]:
        """With the queue full: may this arrival displace a queued waiter?
        Only when the arriving tenant sits UNDER its fair share of the
        whole gate (capacity / tenants present, the classic max-min
        bound) while some waiter's tenant sits OVER its own — then the
        most-over-share tenant's NEWEST waiter is the victim (newest
        first mirrors the engine's preemption discipline: the least
        sunk-cost work yields). Returns the victim's record, or None
        (the arrival sheds as plain queue_full). Caller holds _cv."""
        if tenant is None or not self._waiters:
            return None
        present = set(self._tenant_gate)
        present.add(tenant)
        share = (self.max_concurrency + self.max_queue) / len(present)
        if self._tenant_gate.get(tenant, 0) > share:
            # the arrival itself is over-share (its own count includes
            # this very request): no displacement — it sheds
            return None
        victim, victim_count = None, share
        for rec in reversed(self._waiters):
            t = rec["tenant"]
            if t is None or t == tenant or rec["shed"]:
                continue
            c = self._tenant_gate.get(t, 0)
            if c > victim_count:
                victim, victim_count = rec, c
        return victim

    def _acquire_locked(self, deadline: Optional[Deadline],
                        tenant: Optional[str]) -> None:
        if self.active < self.max_concurrency and self.waiting == 0:
            self.active += 1
            return
        if self.waiting >= self.max_queue:
            victim = self._fair_share_victim(tenant)
            if victim is None:
                self._reject("queue_full", 429, self.retry_after_s,
                             tenant=tenant)
            # displace: the victim wakes, sees its shed mark and rejects
            # itself with reason="fair_share"; this arrival queues in its
            # place (waiting transiently overshoots max_queue by one
            # until the victim unwinds — bounded, never cumulative)
            victim["shed"] = True
            self._cv.notify_all()
        hint = self.saturation_hint
        if hint is not None and hint():
            rec = self.reclaimable_hint
            if rec is None or not rec():
                self._reject("pool_exhausted", 429, self.retry_after_s,
                             tenant=tenant)
            # else: the pool is full of demotable cache warmth — the
            # scheduler reclaims it on its next sweep, so this request
            # waits its bounded turn instead of bouncing a 429
        wrec = {"tenant": tenant, "shed": False}
        self._waiters.append(wrec)
        self.waiting += 1
        try:
            while self.active >= self.max_concurrency:
                if wrec["shed"]:
                    # displaced by an under-share tenant's arrival (the
                    # fair-share branch above): this waiter sheds so the
                    # queue slot changes hands
                    self._reject("fair_share", 429, self.retry_after_s,
                                 tenant=tenant)
                if self._draining:
                    # a drain beginning while we queued: shed NOW —
                    # queued work is exactly what a drain refuses to
                    # start (_reject's raise unwinds through finally)
                    self._reject("draining", 503,
                                 self._drain_retry_after_s, tenant=tenant)
                if deadline is not None:
                    if deadline.expired():
                        fam = self.deadline_counter
                        if fam is not None:
                            fam.labels(stage="queue").inc()
                        raise DeadlineExceeded("queue", deadline.budget_ms)
                    self._cv.wait(timeout=deadline.wait_timeout())
                else:
                    self._cv.wait()
            self.active += 1
        finally:
            self.waiting -= 1
            self._waiters.remove(wrec)

    def _release(self, tenant: Optional[str] = None) -> None:
        with self._cv:
            self._gate_dec_locked(tenant)
            self.active -= 1
            self._cv.notify()

    # -- public ----------------------------------------------------------
    @contextmanager
    def admit(self, deadline: Optional[Deadline] = None,
              tenant: Optional[str] = None):
        """Hold one admission slot for the duration of the request.

        Raises :class:`AdmissionRejected` (shed) or
        :class:`DeadlineExceeded` (stage ``queue``) instead of waiting
        unboundedly. ``tenant`` (edge-interned) attributes any shed to the
        tenant that suffered it — per-tenant shed counts are the signal a
        fair-share admission policy will act on.
        """
        self._acquire(deadline, tenant=tenant)
        try:
            yield
        finally:
            self._release(tenant)

    def queue_depth(self) -> int:
        """Requests currently waiting at the gate (for the depth gauge)."""
        return self.waiting

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, retry_after_s: Optional[float] = None) -> None:
        """Flip the gate to draining: every queued waiter wakes and sheds
        503 reason="draining"; every later arrival sheds at the door.
        Idempotent; there is deliberately NO undrain — a draining process
        exits (tests rebuild the gate instead)."""
        with self._cv:
            if retry_after_s is not None:
                self._drain_retry_after_s = float(retry_after_s)
            self._draining = True
            self._cv.notify_all()
