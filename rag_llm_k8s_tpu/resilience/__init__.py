"""Resilience layer: the serving path's behavior UNDER stress.

PR 2/3 built the telemetry and the SLO decision layer; this package is the
*actuation* side — what the service does when the signals go red instead of
just reporting them:

- :mod:`admission` — a bounded admission gate in front of both engine modes.
  Over-cap requests get an immediate 429/503 with ``Retry-After`` instead of
  an unbounded queue wait (the seed's ``queue.Queue`` grows without limit
  under a burst);
- :mod:`deadline` — end-to-end per-request deadlines checked at every stage
  boundary, with mid-decode slot eviction in the continuous engine so an
  abandoned request stops burning a decode slot;
- :mod:`breaker` — a sliding-window circuit breaker over engine resets:
  N resets inside the window flip ``/healthz`` readiness to 503 so
  Kubernetes drains the pod instead of hammering a sick device;
- :mod:`faults` — a deterministic fault-injection harness (named sites,
  armed via ``TPU_RAG_FAULTS`` or the debug endpoint) that lets the chaos
  suite prove shedding, eviction, recovery, and breaker behavior on CPU.

Everything here is stdlib-only on purpose: the injection sites live in
modules (store, encoder) that must stay importable without JAX warm.
"""

from rag_llm_k8s_tpu.resilience.admission import AdmissionController, AdmissionRejected
from rag_llm_k8s_tpu.resilience.breaker import CircuitBreaker
from rag_llm_k8s_tpu.resilience.deadline import Deadline, DeadlineExceeded

__all__ = [
    "AdmissionController",
    "AdmissionRejected",
    "CircuitBreaker",
    "Deadline",
    "DeadlineExceeded",
]
