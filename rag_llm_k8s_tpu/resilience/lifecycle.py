"""Graceful-shutdown lifecycle: the drain coordinator (ISSUE 19).

Everything in resilience/ so far survives failures *inside* the process —
resets, deadlines, pool exhaustion. Process death was still a cliff: a
SIGTERM (every k8s roll, reschedule, and node drain sends one) killed
in-flight decodes mid-stream and turned each into a client-visible error.
This module is the state machine that turns SIGTERM into a *protocol*:

    serving ──begin_drain()──▶ draining ──in-flight == 0──▶ drained
                                  │                            │
                                  └──deadline overrun──────────┤
                                     (shed + drain_timeout     │
                                      incident)                ▼
                                                       persist + exit

- **serving → draining** — triggered by SIGTERM (server/main.py) or
  ``POST /drain`` (the deployment's preStop hook). The admission gate
  flips to shed every *queued* and *new* request with 503
  ``reason="draining"`` + Retry-After (resilience/admission.py), and
  ``/healthz`` readiness goes 503 ``status="draining"`` so the k8s
  endpoint controller stops routing here — the same flip mechanics the
  breaker uses, for a planned reason instead of a sick one.
- **draining → drained** — a watcher polls the in-flight count. Work
  already past the gate runs to completion; nothing new starts. When the
  count hits zero (or the drain deadline overruns — then the stragglers
  are abandoned where they stand and a ``drain_timeout`` incident bundle
  captures who), the coordinator runs its persist step (WAL sync + the
  prefix cache's warmth manifest — the state a warm restart resumes from)
  and calls ``exit_fn``.

The coordinator never undrains: a draining process exits. Every
collaborator is injected (``active_fn``, ``persist_fn``, ``exit_fn``,
``incident_hook``, ``clock``/``sleep``) so the whole machine is provable
in-process without signals, sleeps, or a real exit.

Knobs: ``TPU_RAG_DRAIN_DEADLINE_S`` / ``TPU_RAG_DRAIN_RETRY_AFTER_S``
(core/config.py::ResilienceConfig) — the deadline must fit inside the
pod's ``terminationGracePeriodSeconds`` with margin for the persist step.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from rag_llm_k8s_tpu.obs import flight
from rag_llm_k8s_tpu.resilience.admission import AdmissionController

__all__ = ["LifecycleCoordinator", "SERVING", "DRAINING", "DRAINED"]

logger = logging.getLogger(__name__)

SERVING = "serving"
DRAINING = "draining"
DRAINED = "drained"


class LifecycleCoordinator:
    """Coordinates one irreversible serving → draining → drained pass.

    Thread-safe; ``begin_drain`` is idempotent (the first trigger wins —
    a SIGTERM racing the preStop hook's ``POST /drain`` must not run two
    drains). The watcher runs on a daemon thread so a wedged in-flight
    request can never block process teardown past the deadline.
    """

    def __init__(
        self,
        admission: Optional[AdmissionController] = None,
        deadline_s: float = 25.0,
        retry_after_s: float = 2.0,
        poll_interval_s: float = 0.05,
        active_fn: Optional[Callable[[], int]] = None,
        persist_fn: Optional[Callable[[], None]] = None,
        exit_fn: Optional[Callable[[], None]] = None,
        incident_hook: Optional[Callable[[str], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s={deadline_s}: expected > 0")
        self.admission = admission
        self.deadline_s = float(deadline_s)
        self.retry_after_s = float(retry_after_s)
        self.poll_interval_s = float(poll_interval_s)
        # in-flight source: defaults to the gate's active count — work
        # past the gate is exactly the work a drain waits for
        self._active_fn = active_fn or (
            (lambda: admission.active) if admission is not None else (lambda: 0)
        )
        self.persist_fn = persist_fn
        self.exit_fn = exit_fn
        self.incident_hook = incident_hook
        self.clock = clock
        self.sleep = sleep
        self._lock = threading.Lock()
        self._state = SERVING
        self._reason: Optional[str] = None
        self._watcher: Optional[threading.Thread] = None
        self._drained = threading.Event()
        self.timed_out = False
        self.stragglers = 0  # in-flight abandoned at the deadline

    # -- read ------------------------------------------------------------
    @property
    def state(self) -> str:
        return self._state

    @property
    def draining(self) -> bool:
        """True from the first begin_drain on — the readiness probe's
        signal (``/healthz`` reports 503 ``status="draining"``)."""
        return self._state != SERVING

    @property
    def reason(self) -> Optional[str]:
        return self._reason

    # -- write -----------------------------------------------------------
    def begin_drain(self, reason: str = "sigterm") -> bool:
        """Start the one drain pass. Returns True when THIS call started
        it, False when a drain was already running (idempotent)."""
        with self._lock:
            if self._state != SERVING:
                return False
            self._state = DRAINING
            self._reason = reason
        in_flight = self._safe_active()
        flight.emit("drain", phase="begin", reason=reason,
                    in_flight=in_flight)
        logger.info("drain began (reason=%s, in_flight=%d, deadline=%.1fs)",
                    reason, in_flight, self.deadline_s)
        if self.admission is not None:
            self.admission.drain(self.retry_after_s)
        watcher = threading.Thread(
            target=self._watch, name="lifecycle-drain", daemon=True
        )
        with self._lock:
            self._watcher = watcher
        watcher.start()
        return True

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        """Block until the pass (including persist) finished — the preStop
        hook and tests wait on this, never on a sleep."""
        return self._drained.wait(timeout)

    # -- internals -------------------------------------------------------
    def _safe_active(self) -> int:
        try:
            return int(self._active_fn())
        except Exception:  # noqa: BLE001 — a broken probe must not stall exit
            logger.exception("drain active_fn failed; treating as 0")
            return 0

    def _watch(self) -> None:
        deadline = self.clock() + self.deadline_s
        while self._safe_active() > 0 and self.clock() < deadline:
            self.sleep(self.poll_interval_s)
        stragglers = self._safe_active()
        if stragglers > 0:
            # deadline overrun: the pod is being killed anyway — journal
            # WHO was abandoned (the WAL's restore pass picks them up) and
            # spool the post-mortem before the persist step
            self.timed_out = True
            self.stragglers = stragglers
            flight.emit("drain", phase="timeout", in_flight=stragglers,
                        deadline_s=self.deadline_s)
            logger.warning("drain deadline (%.1fs) overran with %d in flight",
                           self.deadline_s, stragglers)
            hook = self.incident_hook
            if hook is not None:
                try:
                    hook("drain_timeout")
                except Exception:  # noqa: BLE001 — capture must not stall exit
                    logger.exception("drain_timeout incident capture failed")
        if self.persist_fn is not None:
            try:
                self.persist_fn()
            except Exception:  # noqa: BLE001 — persist is best-effort
                logger.exception("drain persist step failed")
        flight.emit("drain", phase="complete",
                    in_flight=stragglers, timed_out=self.timed_out)
        with self._lock:
            self._state = DRAINED
        self._drained.set()
        if self.exit_fn is not None:
            self.exit_fn()
