"""Deterministic fault injection for the serving path.

Production resilience claims ("an engine reset is invisible to the client",
"a queue over cap sheds instead of blocking") are only claims until a test
can MAKE the fault happen on demand. This module is the switchboard: code
at a handful of named *sites* calls :func:`maybe_fail`, which is a no-op in
normal operation and raises :class:`InjectedFault` when the site is armed.

Arming is count-based and deterministic — ``arm("decode_step", times=2)``
fires the next two traversals of that site and then disarms itself — so a
chaos test asserts exact behavior (first submit hits the reset, the
resubmit succeeds) rather than probabilistic flakiness.

Three ways to arm:

- programmatic (the chaos suite): ``faults.arm(site, times)`` / ``clear()``;
- environment (``make chaos`` / a staging pod): ``TPU_RAG_FAULTS`` as a
  ``site:count`` list, e.g. ``TPU_RAG_FAULTS=decode_step:1,embed:2``
  (``TPU_RAG_FAULTS=1`` enables the debug endpoint without arming anything);
- HTTP (a running server with the env flag set): ``POST /debug/faults``
  with ``{"site": ..., "times": N}`` — gated on the env flag so a
  production pod can never be fault-armed remotely by default.

The site catalog (``SITES``) is closed on purpose: a typo'd site name is a
programming error, not a silently-never-firing fault.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

__all__ = [
    "SITES",
    "InjectedFault",
    "arm",
    "arm_from_env",
    "armed",
    "clear",
    "endpoint_enabled",
    "maybe_fail",
]

# Every call site that can be armed, with the failure it models:
#   store_lookup — the vector store's result materialization (index corruption
#                  / a wedged mmap);
#   embed        — the encoder forward (device fault during embedding);
#   insert       — the continuous engine's KV splice (fires inside the donated
#                  region, so it triggers the EngineStateLost reset path);
#   decode_step  — the continuous engine's decode step (device fault mid-
#                  generation — the recovery/resubmit path's trigger);
#   generate     — the one-shot engine's generate call (coalesce-mode
#                  equivalent of decode_step);
#   lookahead_retrieve — the lookahead executor's worker-side retrieval
#                  (rag/lookahead.py): a failed speculation must fall back
#                  to the inline retrieve path and release everything it
#                  staged — never fail the request.
#   kv_swap_in   — a cold-tier host→HBM KV swap-in (engine/prefix_cache.py
#                  and the paged prestage scatter): a failed swap must fall
#                  back to recompute-from-tokens, release the host buffer,
#                  and leak zero blocks on either substrate.
#   chunk_splice — a chunk-granular prefix-reuse splice (engine/
#                  prefix_cache.py rerotate path and the paged per-chunk
#                  block assembly in engine/continuous.py): a failed splice
#                  must fall back to recompute-from-tokens (cache) or the
#                  buffer-scatter path (pool) and leak zero blocks/entries.
#   migrate      — a prefill→decode pool-block hand-off landing on the
#                  decode-role engine (engine/continuous.py import_request):
#                  fires inside the donated region, so the decode engine
#                  resets (EngineStateLost) and the scheduler re-prefills
#                  prompt+emitted there — streams stay byte-identical and
#                  neither engine leaks a block (docs/ROUTER.md).
SITES = (
    "store_lookup", "embed", "insert", "decode_step", "generate",
    "lookahead_retrieve", "kv_swap_in", "chunk_splice", "migrate",
)

ENV_VAR = "TPU_RAG_FAULTS"


class InjectedFault(RuntimeError):
    """A deliberately injected failure (carries its site name)."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at site {site!r}")
        self.site = site


_lock = threading.Lock()
_armed: Dict[str, int] = {}


def _check_site(site: str) -> None:
    if site not in SITES:
        raise ValueError(f"unknown fault site {site!r}; sites: {SITES}")


def arm(site: str, times: int = 1) -> None:
    """Arm ``site`` to fail its next ``times`` traversals."""
    _check_site(site)
    if times < 1:
        raise ValueError(f"times={times}: expected >= 1")
    with _lock:
        _armed[site] = times


def clear(site: Optional[str] = None) -> None:
    """Disarm one site, or everything when ``site`` is None."""
    with _lock:
        if site is None:
            _armed.clear()
        else:
            _armed.pop(site, None)


def armed() -> Dict[str, int]:
    """Snapshot of remaining failure counts per armed site."""
    with _lock:
        return dict(_armed)


def maybe_fail(site: str) -> None:
    """The injection point. Free when nothing is armed (one dict read)."""
    if not _armed:  # benign race: arming concurrently just delays one shot
        return
    with _lock:
        n = _armed.get(site, 0)
        if n <= 0:
            return
        if n == 1:
            del _armed[site]
        else:
            _armed[site] = n - 1
    raise InjectedFault(site)


def endpoint_enabled(env: Optional[dict] = None) -> bool:
    """Whether the ``/debug/faults`` endpoint may arm sites: only when the
    operator set ``TPU_RAG_FAULTS`` (to anything) at process start."""
    env = os.environ if env is None else env
    return ENV_VAR in env


def arm_from_env(env: Optional[dict] = None) -> Dict[str, int]:
    """Parse ``TPU_RAG_FAULTS`` and arm the listed sites.

    Grammar: comma-separated ``site[:count]`` entries (count defaults to 1).
    The bare values ``""``/``"0"``/``"1"`` arm nothing — they exist so an
    operator can enable the debug endpoint without pre-arming a fault.
    A malformed entry raises: a chaos run with a typo'd site must fail
    loudly, not run green having injected nothing.
    """
    env = os.environ if env is None else env
    spec = env.get(ENV_VAR, "").strip()
    if spec in ("", "0", "1"):
        return {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            site, _, cnt = part.partition(":")
            try:
                times = int(cnt)
            except ValueError as e:
                raise ValueError(
                    f"{ENV_VAR}={spec!r}: bad count in {part!r}"
                ) from e
        else:
            site, times = part, 1
        arm(site.strip(), times)
    return armed()
