"""Circuit breaker over engine resets.

One ``EngineStateLost`` is transient — the scheduler resubmits the in-flight
prompts and the client never notices. A *storm* of resets (a genuinely sick
device, an OOM loop, a broken executable after a driver update) is
different: every reset re-runs full prefills for every in-flight request,
so a pod in a reset loop burns accelerator time making zero progress while
``/healthz`` keeps reporting ready and Kubernetes keeps routing traffic in.

The breaker is a sliding-window event counter: ``record_reset()`` per
engine reset; :attr:`open` when ``threshold`` resets land inside
``window_s``. The server's readiness probe returns 503 while open, so
Kubernetes drains the pod (liveness stays green — a restart would just
replay warmup into the same sick device). The breaker self-heals: once
enough resets age out of the window it closes again, with no half-open
bookkeeping to get wrong — admission control already rate-limits the
traffic that could re-trip it.

``clock`` is injectable for tests.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List

from rag_llm_k8s_tpu.obs import flight

__all__ = ["CircuitBreaker"]


class CircuitBreaker:
    def __init__(
        self,
        threshold: int = 3,
        window_s: float = 300.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if threshold < 1:
            raise ValueError(f"threshold={threshold}: expected >= 1")
        if window_s <= 0:
            raise ValueError(f"window_s={window_s}: expected > 0")
        self.threshold = threshold
        self.window_s = window_s
        self.clock = clock
        self._lock = threading.Lock()
        self._events: List[float] = []  # reset timestamps inside the window
        # observability hooks (set by the service; both optional):
        # on_reset() fires after EVERY recorded reset, on_open() on the
        # closed→open transition only — the incident spooler's reset-storm
        # and breaker-flip bundle triggers (obs/flight.py). Invoked OUTSIDE
        # the breaker's lock: a hook that writes a bundle to disk must not
        # serialize readiness probes.
        self.on_reset = None
        self.on_open = None

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0] <= cutoff:
            self._events.pop(0)

    def record_reset(self) -> None:
        now = self.clock()
        with self._lock:
            self._prune(now)
            was_open = len(self._events) >= self.threshold
            self._events.append(now)
            flipped = not was_open and len(self._events) >= self.threshold
            n = len(self._events)
        if flipped:
            flight.emit("breaker_open", resets=n)
        hooks = ([self.on_open] if flipped else []) + [self.on_reset]
        for hook in hooks:
            if hook is None:
                continue
            try:
                hook()
            except Exception:  # noqa: BLE001 — a hook must not break recording
                pass

    @property
    def open(self) -> bool:
        with self._lock:
            self._prune(self.clock())
            return len(self._events) >= self.threshold

    def recent_resets(self) -> int:
        with self._lock:
            self._prune(self.clock())
            return len(self._events)

    def retry_after_s(self) -> float:
        """Seconds until the breaker could close (the tripping reset ages
        out) — the ``Retry-After`` a shed client is told. 0 when closed."""
        with self._lock:
            now = self.clock()
            self._prune(now)
            if len(self._events) < self.threshold:
                return 0.0
            # closes when the event holding the count at threshold expires
            t_close = self._events[len(self._events) - self.threshold] + self.window_s
            return max(0.0, t_close - now)
