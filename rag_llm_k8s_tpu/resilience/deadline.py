"""Per-request end-to-end deadlines.

The seed had exactly one timeout in the whole serving path — a hardcoded
``th.join(timeout=120)`` on the ids-fetch thread — so a request could queue,
retrieve, and decode indefinitely while its client had long since hung up.
A :class:`Deadline` is carried from the HTTP edge (body ``deadline_ms`` /
``x-request-deadline-ms`` header, default from ``ResilienceConfig``) through
every stage boundary; each boundary calls :meth:`Deadline.check` and an
expired request fails with :class:`DeadlineExceeded` naming the stage it
died in (the ``rag_deadline_exceeded_total{stage}`` family counts them).

The continuous scheduler additionally EVICTS the expired request's decode
slot (see ``ContinuousScheduler``) — without that, a timed-out request keeps
decoding into a slot nobody will ever read, which under sustained overload
converges to a batch full of zombies.

``clock`` is injectable so tests expire deadlines without sleeping.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from rag_llm_k8s_tpu.obs import flight

__all__ = ["Deadline", "DeadlineExceeded"]

# stage labels used across the serving path (documented in RESILIENCE.md):
#   queue    — expired waiting for admission or in a scheduler queue
#   retrieve — expired during/after embed+kNN
#   assemble — expired during prompt assembly
#   generate — the blocking submit timed out (coalesce mode: the whole
#              prefill+decode is one device call, not separable)
#   decode   — evicted mid-decode by the continuous scheduler
STAGES = ("queue", "retrieve", "assemble", "generate", "decode")


class DeadlineExceeded(TimeoutError):
    """The request's end-to-end deadline expired at ``stage``."""

    def __init__(self, stage: str, budget_ms: Optional[float] = None):
        msg = f"request deadline exceeded at stage {stage!r}"
        if budget_ms is not None:
            msg += f" (budget {budget_ms:.0f} ms)"
        super().__init__(msg)
        self.stage = stage
        self.budget_ms = budget_ms
        # constructing this exception IS the decision point — every raise
        # site (HTTP edge, stage boundaries, scheduler eviction sweep)
        # journals through this one line
        flight.emit("deadline", stage=stage)


class Deadline:
    """An absolute point in time a request must not outlive."""

    __slots__ = ("t_deadline", "budget_ms", "clock")

    def __init__(self, budget_ms: float, clock: Callable[[], float] = time.monotonic):
        if budget_ms <= 0:
            raise ValueError(f"budget_ms={budget_ms}: expected > 0")
        self.clock = clock
        self.budget_ms = float(budget_ms)
        self.t_deadline = clock() + budget_ms / 1e3

    def remaining(self) -> float:
        """Seconds left (negative when expired)."""
        return self.t_deadline - self.clock()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, stage: str) -> None:
        """Raise :class:`DeadlineExceeded` if the budget is spent."""
        if self.expired():
            raise DeadlineExceeded(stage, self.budget_ms)

    def wait_timeout(self, floor_s: float = 1e-3) -> float:
        """The remaining budget as a blocking-wait timeout (floored at a
        tiny positive value so an already-expired deadline still makes one
        fast-failing wait instead of an invalid negative timeout)."""
        return max(self.remaining(), floor_s)
