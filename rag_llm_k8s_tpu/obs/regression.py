"""Bench regression gate: direction-aware metric comparison.

``bench.py`` emits one JSON line of headline metrics; this module compares a
fresh line against a committed baseline (``BENCH_BASELINE.json`` or any
previous ``BENCH_r*.json``) with per-metric tolerance bands and **direction
awareness** — latency going up is a regression, tokens/sec going down is a
regression, and a metric moving the GOOD way is never flagged no matter how
far it moves. Wired as ``make bench-gate`` (scripts/bench_gate.py) so a perf
regression is caught before merge instead of three rounds later in a
VERDICT diff.

Metric classification is by key pattern over the FLATTENED document (nested
dicts join with '.'), ordered first-match-wins:

- higher-is-better: throughputs (``tok_per_s``, ``qps``, ``chunks_per_s``,
  ``steps_per_s``), efficiency ratios (``mfu``, ``vs_baseline``,
  ``tokens_per_verify``, ``prefix_prefill_reduction``);
- lower-is-better: durations (``*_ms``, ``*_s``, ``*_seconds``) and byte
  sizes (``snapshot_bytes``);
- ignored: counts/config echoes (``*_n``, ``batch``, booleans, strings,
  lists, ``truncated`` markers) — they are workload descriptors, not
  performance;
- band (ideal = 1.0): fidelity ratios (``steps_per_s_ratio``,
  ``cost_ratio`` — the replay simulator's predicted-over-measured figures,
  docs/REPLAY.md) — judged against the ABSOLUTE ``1.0 ± tolerance`` band,
  not against the baseline, because drifting high is exactly as wrong as
  drifting low.

Keys present in only one document are reported as ``missing`` (information,
not failure, unless ``strict``): bench legs evolve round over round and the
gate must not freeze the schema.
"""

from __future__ import annotations

import json
import math
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

__all__ = [
    "classify",
    "flatten",
    "compare",
    "comparable_overlap",
    "Finding",
    "DEFAULT_TOLERANCE",
]

# relative band: the shared-chip bench shows run-to-run contention spread
# (BENCH_r* p50 passes differ by ~5-10%); 25% flags real regressions while
# riding out the noise. Tighten per-invocation with --tolerance.
DEFAULT_TOLERANCE = 0.25

# (pattern, direction) — first match wins; direction 'ignore' short-circuits
_RULES: Tuple[Tuple[re.Pattern, str], ...] = tuple(
    (re.compile(p), d)
    for p, d in (
        # -- ignore: workload/config echoes and markers --------------------
        (r"(^|\.)(n|query_n|metric|unit)$", "ignore"),
        # the headline: bench.py's "value" is decode tokens/sec/chip
        (r"(^|\.)value$", "higher"),
        (r"(_|^|\.)(batch|bucket|concurrency|dim|vectors?|chunks|steps)$", "ignore"),
        (r"passes", "ignore"),
        (r"truncated|legs_skipped|quant$|identical", "ignore"),
        (r"fetches_per_query|verify_steps|spec_verify", "ignore"),
        (r"alpha|top1_prob|longctx_T", "ignore"),
        (r"tokens_computed|tokens_reused|index_vectors", "ignore"),
        # environment property (the harness's host link), not repo perf —
        # and the per-round target constant
        (r"tunnel_fetch|target", "ignore"),
        # chunk-reuse leg's exact-policy CONTROL numbers (reported for
        # contrast, deliberately unjudged) — must precede the qps rule
        (r"exact_skip_frac|exact_resolve_qps", "ignore"),
        # -- band: ideal is exactly 1.0 -----------------------------------
        # replay-fidelity leg (ISSUE 17, docs/REPLAY.md): the simulator's
        # predicted-over-measured ratios — must precede the _per_s rule,
        # which would read steps_per_s_ratio=1.4 as an "improvement"
        (r"steps_per_s_ratio|cost_ratio", "band"),
        # -- higher is better ---------------------------------------------
        (r"tok_per_s|tokens_per_sec|per_s$|_per_s(\.|_|$)|qps", "higher"),
        (r"mfu|vs_baseline|tokens_per_verify|reduction", "higher"),
        # paged-KV leg: dense→paged step-rate ratio and the
        # admittable-slots-at-fixed-HBM gain (ISSUE 5 acceptance numbers)
        (r"speedup|_gain$", "higher"),
        # KV-tiering leg (ISSUE 8): servable-capacity multiplier at fixed
        # HBM and the fraction of swap-ins hidden under decode
        (r"effective_capacity_x|hide_rate", "higher"),
        # paged-speculation leg (ISSUE 13): mean accepted draft length per
        # verify window — shrinkage means the draft source stopped firing
        # (the speedups themselves match the "speedup" rule above)
        (r"accept_len_mean", "higher"),
        # chunk-reuse leg (ISSUE 12): prefill tokens skipped on the
        # shuffled-composition stream — shrinkage is a regression; the
        # measured logit error must not grow past its pin either
        (r"prefill_skip_frac", "higher"),
        (r"logit_max_err", "lower"),
        (r"logit_tol", "ignore"),
        # goodput ledger (ISSUE 14): useful-work shares and tokens/$ must
        # not shrink (mfu_* matches the mfu rule above)
        (r"tokens_per_usd|goodput_frac|useful_frac", "higher"),
        # -- lower is better ----------------------------------------------
        # goodput ledger (ISSUE 14): padding-bubble share of busy chip
        # time — growth means admission shapes/batch occupancy regressed
        (r"bubble_frac", "lower"),
        # flight-recorder cost (ISSUE 11): fraction of decode steps/s the
        # journal costs with the recorder on — growth is a regression
        (r"overhead_frac", "lower"),
        (r"_ms($|\.|_)|_s$|seconds|_bytes$", "lower"),
    )
)


def classify(key: str) -> str:
    """'higher' | 'lower' | 'band' | 'ignore' for one flattened key."""
    for rx, direction in _RULES:
        if rx.search(key):
            return direction
    return "ignore"


def flatten(doc: Dict, prefix: str = "") -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k, v in doc.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(flatten(v, key))
        else:
            out[key] = v
    return out


@dataclass(frozen=True)
class Finding:
    key: str
    kind: str  # 'regression' | 'improvement' | 'missing'
    direction: str  # 'higher' | 'lower'
    baseline: Optional[float]
    current: Optional[float]
    ratio: Optional[float]  # current / baseline

    def describe(self) -> str:
        if self.kind == "missing":
            side = "current" if self.current is None else "baseline"
            return f"{self.key}: absent from {side}"
        arrow = "↑" if (self.ratio or 1.0) >= 1.0 else "↓"
        pct = abs((self.ratio or 1.0) - 1.0) * 100.0
        if self.direction == "band":
            off = abs((self.current if self.current is not None else 1.0) - 1.0)
            return (
                f"{self.key}: {self.baseline:g} → {self.current:g} "
                f"({off * 100.0:.1f}% off the 1.0 fidelity ideal)"
            )
        want = "lower" if self.direction == "lower" else "higher"
        return (
            f"{self.key}: {self.baseline:g} → {self.current:g} "
            f"({arrow}{pct:.1f}%, {want}-is-better)"
        )


def _numeric(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    f = float(v)
    return f if math.isfinite(f) else None


def compare(
    current: Dict,
    baseline: Dict,
    tolerance: float = DEFAULT_TOLERANCE,
) -> Dict[str, List[Finding]]:
    """Compare two bench documents → findings bucketed by kind.

    A metric regresses when it moves the BAD way past the relative band:
    lower-is-better: ``current > baseline * (1 + tolerance)``;
    higher-is-better: ``current < baseline * (1 - tolerance)``;
    band (ideal 1.0): ``abs(current - 1) > tolerance`` regardless of the
    baseline — the fidelity contract is absolute.
    Baselines of 0 compare only for direction (any bad nonzero flags).
    """
    cur = flatten(current)
    base = flatten(baseline)
    out: Dict[str, List[Finding]] = {
        "regression": [], "improvement": [], "missing": []
    }
    for key in sorted(set(cur) | set(base)):
        direction = classify(key)
        if direction == "ignore":
            continue
        cv, bv = _numeric(cur.get(key)), _numeric(base.get(key))
        if cv is None and bv is None:
            continue
        if cv is None or bv is None:
            out["missing"].append(Finding(key, "missing", direction, bv, cv, None))
            continue
        ratio = cv / bv if bv else (math.inf if cv > 0 else 1.0)
        if direction == "band":
            # absolute band around the 1.0 ideal — the baseline only
            # matters for "improvement" (moved meaningfully closer to 1)
            bad = abs(cv - 1.0) > tolerance
            good = abs(cv - 1.0) < abs(bv - 1.0) * (1.0 - tolerance)
        elif direction == "lower":
            bad = cv > bv * (1.0 + tolerance) if bv else cv > 0
            good = cv < bv * (1.0 - tolerance)
        else:
            bad = cv < bv * (1.0 - tolerance)
            good = cv > bv * (1.0 + tolerance) if bv else cv > 0
        if bad:
            out["regression"].append(
                Finding(key, "regression", direction, bv, cv, ratio)
            )
        elif good:
            out["improvement"].append(
                Finding(key, "improvement", direction, bv, cv, ratio)
            )
    return out


def load_json(path: str) -> Dict:
    """Load a bench document; tolerates a file whose LAST line is the JSON
    (bench.py prints one line, but logs can precede it in captured runs)
    and unwraps the driver's ``{"parsed": {...}}`` envelope (the
    ``BENCH_r*.json`` artifacts) so any committed round can serve as the
    baseline with the same key namespace a fresh bench emits."""
    with open(path, encoding="utf-8") as f:
        text = f.read().strip()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
        for line in reversed(text.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                doc = json.loads(line)
                break
        if doc is None:
            raise
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc


def comparable_overlap(current: Dict, baseline: Dict) -> List[str]:
    """The flattened keys BOTH documents carry as comparable numerics —
    the gate's judged surface. Empty overlap means the gate would be
    vacuous (nothing judged), which callers must treat as an ERROR, not a
    pass: a schema mismatch silently green-lighting every regression is
    exactly the failure mode this gate exists to prevent."""
    cur, base = flatten(current), flatten(baseline)
    return sorted(
        k for k in set(cur) & set(base)
        if classify(k) != "ignore"
        and _numeric(cur[k]) is not None and _numeric(base[k]) is not None
    )


def schema_check(doc: Dict) -> List[str]:
    """Dry-run validation: the document must parse (caller's job), be a
    JSON object, and carry at least one comparable numeric metric. Returns
    human-readable problems (empty = OK)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"top level must be a JSON object, got {type(doc).__name__}"]
    flat = flatten(doc)
    comparable = [
        k for k, v in flat.items()
        if classify(k) != "ignore" and _numeric(v) is not None
    ]
    if not comparable:
        problems.append(
            "no comparable numeric metrics found (every key classified "
            "'ignore' or non-numeric)"
        )
    return problems
