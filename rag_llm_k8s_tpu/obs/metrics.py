"""Prometheus-grade metrics registry for the serving path.

The seed's telemetry was a sum/count counter dict rendered ad hoc by the
``/metrics`` handler — no percentiles, no types, no labels. This module is
the one registry everything reports into:

- **Counter / Gauge / Histogram** primitives, each optionally *labeled*
  (``histogram.labels(stage="prefill")`` returns a per-label child).
  Histograms use FIXED log-spaced buckets so p50/p95 can be read off any
  scrape (and so ``bench.py`` and a production Prometheus read the *same*
  numbers from the same structure).
- **Lock-cheap hot path**: one uncontended per-child lock acquisition per
  observation — no global registry lock is ever taken to observe, only to
  register (which is rare and idempotent).
- **Callback metrics**: a Counter/Gauge constructed with ``fn=`` reads its
  value at collect time — how live engine stats (generate calls, slot
  occupancy, queue depth, index size) fold into the same scrape without a
  write on their hot paths.
- **Two renderings** of the same state: Prometheus text exposition
  (``render_prometheus``) and a flat JSON snapshot (``snapshot``) for the
  pre-existing JSON consumers (tests, bench) — content negotiation in the
  server picks one; the values are identical by construction
  (tests/test_obs.py pins the equivalence).

Naming: metric names beginning with ``rag_`` are canonical and rendered
verbatim; any other name (the legacy counter-dict names like
``query_decode_tokens``) is prefixed ``tpu_rag_`` in the exposition, which
preserves the seed's scrape surface exactly.
"""

from __future__ import annotations

import math
import re
import threading
from bisect import bisect_left
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "TenantTracker",
    "default_registry",
    "log_buckets",
    "LATENCY_BUCKETS",
    "REQUEST_BUCKETS",
    "TOKEN_LATENCY_BUCKETS",
]


def log_buckets(lo: float, hi: float, factor: float) -> Tuple[float, ...]:
    """Log-spaced histogram upper bounds from ``lo`` until ``hi`` is covered.

    Bounds are rounded to 4 significant figures so the exposition stays
    readable; ``factor`` > 1 keeps them strictly increasing after rounding.
    """
    if lo <= 0 or factor <= 1:
        raise ValueError("log_buckets needs lo > 0 and factor > 1")
    out: List[float] = []
    b = lo
    while True:
        out.append(float(f"{b:.4g}"))
        if b >= hi:
            break
        b *= factor
    return tuple(out)


# coarse general-purpose latency ladder: 0.5 ms .. ~65 s, x2 per bucket
LATENCY_BUCKETS = log_buckets(0.0005, 64.0, 2.0)
# fine end-to-end request ladder (the p50/p95 the bench and dashboards
# read off the histogram): ~12% relative resolution, 5 ms .. ~90 s
REQUEST_BUCKETS = log_buckets(0.005, 90.0, 1.12)
# per-token ladder (TTFT / inter-token): 0.2 ms .. ~2.2 s
TOKEN_LATENCY_BUCKETS = log_buckets(0.0002, 2.0, 1.5)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _escape_label(v: str) -> str:
    """Exposition label-value escaping: backslash, quote, and newline each
    become a two-character escape (a regex prefixing '\\' would leave the
    literal newline in place and split the sample across lines)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """``# HELP`` escaping per the text-format spec: ONLY backslash and
    newline (quotes stay literal in help text — escaping them like label
    values would render ``\\"`` into every docstring that quotes a knob).
    A literal newline would otherwise split the comment and leave a line
    the scraper rejects as an invalid sample."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _canonical(name: str) -> str:
    """Exposition name: ``rag_*`` verbatim, everything else ``tpu_rag_*``
    (the seed's prefix — its scrape surface must not move)."""
    safe = _NAME_RE.sub("_", name)
    return safe if safe.startswith("rag_") else f"tpu_rag_{safe}"


def _fmt_labels(labels: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


class _Child:
    """One (metric, label-set) time series. Base for the typed children."""

    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = threading.Lock()


class Counter(_Child):
    """Monotonic counter. ``fn`` makes it a *callback* counter whose value
    is read at collect time (``inc`` is then a programming error)."""

    __slots__ = ("_value", "_fn")

    def __init__(self, fn: Optional[Callable[[], float]] = None):
        super().__init__()
        self._value = 0.0
        self._fn = fn

    def inc(self, value: float = 1.0) -> None:
        if self._fn is not None:
            raise RuntimeError("callback counter cannot be inc()'d")
        if value < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a broken probe must not 500 /metrics
                return 0.0
        with self._lock:
            return self._value


class Gauge(_Child):
    """Level-valued sample; ``fn`` reads the live value at collect time."""

    __slots__ = ("_value", "_fn")

    def __init__(self, fn: Optional[Callable[[], float]] = None):
        super().__init__()
        self._value = 0.0
        self._fn = fn

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self._value += value

    def dec(self, value: float = 1.0) -> None:
        with self._lock:
            self._value -= value

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a broken probe must not 500 /metrics
                return 0.0
        with self._lock:
            return self._value


class Histogram(_Child):
    """Fixed-bucket histogram (log-spaced by default).

    Per-bucket counts are stored non-cumulative and rendered cumulative
    (Prometheus ``le`` semantics, ``+Inf`` implicit last). ``quantile``
    interpolates linearly inside the landing bucket — with log-spaced
    buckets that bounds the relative error by the bucket ratio, which is
    why the request-duration ladder is fine-grained (REQUEST_BUCKETS).
    """

    __slots__ = ("bounds", "_counts", "_sum", "_count")

    def __init__(self, buckets: Sequence[float] = LATENCY_BUCKETS):
        super().__init__()
        bounds = tuple(float(b) for b in buckets)
        if not bounds or any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError("histogram buckets must be strictly increasing")
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # final slot = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        i = bisect_left(self.bounds, value)  # first bound >= value (le)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> Tuple[Tuple[int, ...], float, int]:
        """Consistent ``(per_bucket_counts, sum, count)`` — subtractable, so
        a caller can diff two snapshots and take quantiles of the window
        in between (bench.py's per-pass p50/p95)."""
        with self._lock:
            return tuple(self._counts), self._sum, self._count

    def quantile(
        self,
        q: float,
        snapshot: Optional[Tuple[Tuple[int, ...], float, int]] = None,
    ) -> Optional[float]:
        """Estimated ``q``-quantile (0..1) with linear interpolation inside
        the landing bucket; None when empty. ``snapshot`` lets callers take
        quantiles of a diffed window instead of the lifetime state."""
        counts, _, total = snapshot if snapshot is not None else self.snapshot()
        if total <= 0:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = 0.0 if i == 0 else self.bounds[i - 1]
                hi = self.bounds[i] if i < len(self.bounds) else lo
                frac = (rank - cum) / c
                return lo + (hi - lo) * max(0.0, min(1.0, frac))
            cum += c
        return self.bounds[-1]

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One registered metric name: kind + help + label children.

    Unlabeled metrics hold exactly one child under the empty label tuple.
    """

    def __init__(self, name: str, kind: str, help: str, **child_kw):
        self.name = name
        self.kind = kind
        self.help = help
        self._child_kw = child_kw
        self._lock = threading.Lock()
        self._children: "Dict[Tuple[Tuple[str, str], ...], _Child]" = {}

    def labels(self, **labelvalues: str):
        key = tuple(sorted((k, str(v)) for k, v in labelvalues.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _KINDS[self.kind](**self._child_kw)
                self._children[key] = child
        return child

    def labels_callback(self, fn: Callable[[], float], **labelvalues: str):
        """A per-label-set CALLBACK child: unlike the family-wide ``fn=``
        (shared via child_kw), each label set reads its own probe at collect
        time — how per-device gauges and the SLO burn gauges fold live state
        into one labeled family. Idempotent: re-registering swaps the probe."""
        if self.kind == "histogram":
            raise ValueError("histograms cannot be callback-valued")
        child = self.labels(**labelvalues)
        child._fn = fn
        return child

    def items(self):
        with self._lock:
            return list(self._children.items())

    def prune_label(self, label: str, keep: Sequence[str]) -> int:
        """Drop every child whose ``label`` value is NOT in ``keep``.

        The cardinality-bound enforcement point: when :class:`TenantTracker`
        demotes a tenant out of the tracked set, its children leave the
        exposition so the family can never accumulate more series than the
        tracked set allows. Children without the label at all (the empty
        label set, or differently-labeled series) are untouched. Returns
        the number of children removed."""
        keep_set = {str(k) for k in keep}
        with self._lock:
            doomed = [
                key for key in self._children
                if any(n == label and v not in keep_set for n, v in key)
            ]
            for key in doomed:
                del self._children[key]
        return len(doomed)


class MetricsRegistry:
    """Get-or-create registry of metric families + the legacy facade.

    The legacy facade (``inc``/``observe``/``snapshot``) preserves the
    seed's ``_Metrics`` API byte-for-byte so every pre-existing consumer
    (bench.py's ``query_single_fetch`` reads, the JSON ``/metrics`` tests)
    keeps working; ``observe(name, v)`` maintains the old ``{name}_sum`` /
    ``{name}_count`` counter pair.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    # -- registration (get-or-create, idempotent) -----------------------
    def _family(self, name: str, kind: str, help: str, **child_kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help, **child_kw)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, not {kind}"
                )
            return fam

    def counter(self, name: str, help: str = "",
                fn: Optional[Callable[[], float]] = None):
        return self._family(name, "counter", help, fn=fn).labels()

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None):
        return self._family(name, "gauge", help, fn=fn).labels()

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS):
        return self._family(name, "histogram", help, buckets=buckets).labels()

    def labeled_histogram(self, name: str, help: str = "",
                          buckets: Sequence[float] = LATENCY_BUCKETS) -> _Family:
        return self._family(name, "histogram", help, buckets=buckets)

    def labeled_counter(self, name: str, help: str = "") -> _Family:
        return self._family(name, "counter", help)

    def labeled_gauge(self, name: str, help: str = "") -> _Family:
        return self._family(name, "gauge", help)

    def get_family(self, name: str) -> Optional[_Family]:
        """The registered family (or None) — read-side consumers (the SLO
        engine windows over the request histograms) find their sources here
        without creating empty families as a side effect."""
        with self._lock:
            return self._families.get(name)

    # -- legacy facade (the seed's _Metrics API) ------------------------
    def observe(self, name: str, value: float) -> None:
        self.counter(f"{name}_sum").inc(value)
        self.counter(f"{name}_count").inc(1)

    def inc(self, name: str, value: float = 1) -> None:
        self.counter(name).inc(value)

    # -- renderings ------------------------------------------------------
    def _families_sorted(self) -> List[_Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def snapshot(self) -> Dict[str, float]:
        """Flat JSON view: counters/gauges by name, histograms as
        ``name_sum``/``name_count`` (labeled children are summed — the JSON
        view is the coarse one; the exposition carries the label detail)."""
        out: Dict[str, float] = {}
        for fam in self._families_sorted():
            items = fam.items()
            if not items:
                # a labeled family with no children yet has no samples in
                # the exposition either — the two views must carry the
                # same names (bounded tenant families sit empty until
                # their first tracked tenant)
                continue
            if fam.kind == "histogram":
                s = c = 0.0
                for _, child in items:
                    s += child.sum
                    c += child.count
                out[f"{fam.name}_sum"] = s
                out[f"{fam.name}_count"] = c
            else:
                total = 0.0
                for _, child in items:
                    total += child.value
                out[fam.name] = total
        return out

    def render_prometheus(self) -> str:
        """Text exposition format 0.0.4 — the thing a scraper consumes."""
        lines: List[str] = []
        for fam in self._families_sorted():
            name = _canonical(fam.name)
            if fam.help:
                lines.append(f"# HELP {name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {name} {fam.kind}")
            for labels, child in sorted(fam.items()):
                if fam.kind == "histogram":
                    counts, hsum, count = child.snapshot()
                    cum = 0
                    for bound, c in zip(child.bounds, counts):
                        cum += c
                        le = _fmt_labels(labels, f'le="{_fmt_value(bound)}"')
                        lines.append(f"{name}_bucket{le} {cum}")
                    le = _fmt_labels(labels, 'le="+Inf"')
                    lines.append(f"{name}_bucket{le} {count}")
                    lines.append(f"{name}_sum{_fmt_labels(labels)} {_fmt_value(hsum)}")
                    lines.append(f"{name}_count{_fmt_labels(labels)} {count}")
                else:
                    lines.append(
                        f"{name}{_fmt_labels(labels)} {_fmt_value(child.value)}"
                    )
        return "\n".join(lines) + "\n"


class TenantTracker:
    """Cardinality-bounded tenant label interner: top-K + ``__other__``.

    The registry's labeled families create a child per distinct label
    value — fed raw tenant ids from millions-of-users traffic they would
    grow without bound (the same failure PR 15 closed for
    ``rag_spec_acceptance_rate`` by bucketing). This tracker is the one
    gate tenant ids pass through before they become label values:

    - ``intern(tenant)`` counts the tenant with a bounded *space-saving*
      frequency table (capacity entries; a newcomer evicts the global
      minimum and inherits its count as an overestimate bound) and returns
      the tenant's own name only while it sits in the current top-K by
      request count — everything else maps to :data:`TenantTracker.OTHER`.
      A cold tenant that turns hot re-promotes the moment its count passes
      the tracked minimum (its pre-promotion history stays in
      ``__other__`` — attribution is forward-looking by design).
    - Families registered via ``bind(family, label="tenant")`` are pruned
      on every demotion AND on every ``prune()`` (the scrape path calls
      it), so no request pattern can hold more than K+1 tenant children
      per family: K tracked names plus the overflow bucket.

    Thread-safe: the count table and tracked set live under one lock;
    family pruning happens outside it (family locks are per-family).
    """

    OTHER = "__other__"

    def __init__(self, top_k: int = 8, capacity: Optional[int] = None):
        if top_k < 1:
            raise ValueError("TenantTracker needs top_k >= 1")
        self.top_k = int(top_k)
        self.capacity = int(capacity) if capacity else max(8 * self.top_k, 128)
        if self.capacity < self.top_k:
            raise ValueError("TenantTracker capacity must cover top_k")
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._tracked: set = set()
        self._families: List[Tuple[_Family, str]] = []

    def bind(self, family: _Family, label: str = "tenant") -> _Family:
        """Register a family whose ``label`` children this tracker bounds."""
        with self._lock:
            self._families.append((family, label))
        return family

    def intern(self, tenant: str) -> str:
        """Count one request for ``tenant``; return the label value the
        caller may use: the tenant's own name iff currently tracked, else
        ``__other__`` (a client claiming ``__other__`` itself lands in the
        overflow bucket — it can never impersonate a tracked series)."""
        name = str(tenant)
        demoted = False
        with self._lock:
            if name == self.OTHER:
                return self.OTHER
            c = self._counts.get(name)
            if c is not None:
                self._counts[name] = c + 1
            elif len(self._counts) < self.capacity:
                self._counts[name] = 1
            else:
                victim, floor = min(
                    self._counts.items(), key=lambda kv: (kv[1], kv[0])
                )
                del self._counts[victim]
                self._counts[name] = floor + 1
                if victim in self._tracked:
                    self._tracked.discard(victim)
                    demoted = True
            if name not in self._tracked:
                if len(self._tracked) < self.top_k:
                    self._tracked.add(name)
                else:
                    low, low_c = min(
                        ((t, self._counts.get(t, 0)) for t in self._tracked),
                        key=lambda kv: (kv[1], kv[0]),
                    )
                    # strictly greater: ties keep the incumbent, so two
                    # equal-rate tenants don't flap the exposition
                    if self._counts[name] > low_c:
                        self._tracked.discard(low)
                        self._tracked.add(name)
                        demoted = True
            out = name if name in self._tracked else self.OTHER
            keep = tuple(self._tracked) + (self.OTHER,)
            fams = list(self._families) if demoted else ()
        for fam, label in fams:
            fam.prune_label(label, keep)
        return out

    def tracked(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._tracked))

    def prune(self) -> None:
        """Re-assert the bound over every bound family — the scrape path
        calls this so a demotion racing an in-flight ``labels()`` call is
        healed by the next collection at the latest."""
        with self._lock:
            keep = tuple(self._tracked) + (self.OTHER,)
            fams = list(self._families)
        for fam, label in fams:
            fam.prune_label(label, keep)

    def snapshot(self) -> Dict[str, object]:
        """Diagnostics for ``/debug/tenants``: who is tracked and with what
        (overestimate-bounded) request counts."""
        with self._lock:
            tracked = sorted(self._tracked)
            counts = {t: self._counts.get(t, 0) for t in tracked}
            table = len(self._counts)
        return {
            "top_k": self.top_k,
            "capacity": self.capacity,
            "tracked": tracked,
            "counts": counts,
            "table_size": table,
        }


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """Process-wide fallback registry: engines constructed standalone (unit
    tests, scripts) report here; ``RagService`` rebinds its engines to its
    own instance so concurrent services (bench legs) never cross-count."""
    return _DEFAULT
