"""Per-tenant attribution report: ONE renderer for live and offline.

``GET /debug/tenants`` folds the flight recorder's live snapshot through
:func:`state_from_events` + :func:`render_report`; ``flightview
--tenants`` folds an exported journal (or an incident bundle's) through
the *same two functions* loaded by file path — which is why the two
surfaces render byte-identical reports over the same events, and why this
module is STDLIB-ONLY and imports no siblings (it joins ``flight.py`` /
``goodput.py`` / ``shadow.py`` in ragcheck's SIM-PURITY pure set: a
laptop with nothing but a journal file must be able to load it).

Attribution sources, all free-form attrs on events already in the closed
flight catalog:

- ``arrival.tenant`` — the edge-interned tenant (K tracked names +
  ``__other__``; default ``anon``). Also seeds a rid→tenant map so
  events that only carry ``rid`` (``admit``, sim-engine journals)
  attribute correctly.
- ``complete.tenant`` / ``.n_tokens`` / ``.chip_ms`` / ``.cost_usd`` —
  tokens, chip-seconds, and cost per tenant (the goodput ledger's
  per-request attribution, one dimension finer).
- ``shed.tenant`` — admission rejections per tenant (the signal a
  fair-share gate acts on).
- ``shadow_audit.tenant`` / ``.outcome`` — quality audits and divergence
  per tenant.

Events with no tenant anywhere fold into ``anon`` — a pre-tenant journal
renders as one honest unattributed row instead of failing.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

__all__ = [
    "SCHEMA_VERSION",
    "OTHER",
    "new_state",
    "record",
    "state_from_events",
    "render_report",
]

SCHEMA_VERSION = 1
#: the tracker's overflow bucket (mirrors metrics.TenantTracker.OTHER —
#: restated here because this module may not import it)
OTHER = "__other__"
#: tenant of record for events carrying no tenant anywhere
DEFAULT_TENANT = "anon"

#: event types this report consumes (everything else only advances the
#: wall-clock span)
_CONSUMED = ("arrival", "admit", "complete", "shed", "shadow_audit")


def _row() -> Dict[str, float]:
    return {
        "arrivals": 0,
        "admitted": 0,
        "completed": 0,
        "sheds": 0,
        "tokens": 0,
        "chip_s": 0.0,
        "cost_usd": 0.0,
        "audits": 0,
        "diverged": 0,
    }


def new_state() -> Dict[str, object]:
    return {
        "schema_version": SCHEMA_VERSION,
        "tenants": {},
        "events": 0,
        "t_first": None,
        "t_last": None,
        "_rids": {},
    }


def record(state: Dict[str, object], ev: Dict[str, object]) -> None:
    """Fold one flight event (live snapshot dict or journal line) into
    ``state``. Order-sensitive only through the rid→tenant map, which both
    renderers see in the same (seq) order."""
    state["events"] = int(state.get("events", 0)) + 1
    t = ev.get("t")
    if isinstance(t, (int, float)):
        if state["t_first"] is None or t < state["t_first"]:
            state["t_first"] = t
        if state["t_last"] is None or t > state["t_last"]:
            state["t_last"] = t
    et = ev.get("type")
    if et not in _CONSUMED:
        return
    tenants: Dict[str, Dict[str, float]] = state["tenants"]  # type: ignore[assignment]
    rids: Dict[object, str] = state.setdefault("_rids", {})  # type: ignore[assignment]
    tenant = ev.get("tenant")
    rid = ev.get("rid")
    if et == "arrival":
        tenant = str(tenant) if tenant is not None else DEFAULT_TENANT
        if rid is not None:
            rids[rid] = tenant
    else:
        if tenant is None and rid is not None:
            tenant = rids.get(rid)
        tenant = str(tenant) if tenant is not None else DEFAULT_TENANT
    row = tenants.get(tenant)
    if row is None:
        row = tenants[tenant] = _row()
    if et == "arrival":
        row["arrivals"] += 1
    elif et == "admit":
        row["admitted"] += 1
    elif et == "complete":
        row["completed"] += 1
        n = ev.get("n_tokens")
        if isinstance(n, (int, float)):
            row["tokens"] += int(n)
        chip_ms = ev.get("chip_ms")
        if isinstance(chip_ms, (int, float)):
            row["chip_s"] += float(chip_ms) / 1e3
        cost = ev.get("cost_usd")
        if isinstance(cost, (int, float)):
            row["cost_usd"] += float(cost)
    elif et == "shed":
        row["sheds"] += 1
    else:  # shadow_audit
        row["audits"] += 1
        if ev.get("outcome") == "diverged":
            row["diverged"] += 1


def state_from_events(events: Iterable[Dict[str, object]]) -> Dict[str, object]:
    state = new_state()
    for ev in events:
        record(state, ev)
    return state


def render_report(
    state: Dict[str, object], chip_hour_usd: float = 0.0
) -> Dict[str, object]:
    """The report both surfaces serve: rows sorted by chip-seconds
    descending (name-tiebroken — determinism is what makes byte-identity
    testable), shares of the attributed total, and a totals row. When the
    journal predates pricing (no ``cost_usd`` on completes) but the caller
    knows the chip rate, cost is derived from chip-seconds."""
    tenants: Dict[str, Dict[str, float]] = state.get("tenants", {})  # type: ignore[assignment]
    total_chip = sum(r["chip_s"] for r in tenants.values())
    rows: List[Dict[str, object]] = []
    totals = _row()
    for name in sorted(tenants, key=lambda n: (-tenants[n]["chip_s"], n)):
        r = tenants[name]
        cost = r["cost_usd"]
        if not cost and chip_hour_usd:
            cost = r["chip_s"] / 3600.0 * float(chip_hour_usd)
        for k in totals:
            totals[k] += r[k]
        totals["cost_usd"] += cost - r["cost_usd"]  # count the derived form
        rows.append({
            "tenant": name,
            "arrivals": int(r["arrivals"]),
            "admitted": int(r["admitted"]),
            "completed": int(r["completed"]),
            "sheds": int(r["sheds"]),
            "tokens": int(r["tokens"]),
            "chip_s": round(r["chip_s"], 6),
            "chip_share": round(r["chip_s"] / total_chip, 4) if total_chip else 0.0,
            "cost_usd": round(cost, 6),
            "tokens_per_chip_s": (
                round(r["tokens"] / r["chip_s"], 2) if r["chip_s"] else 0.0
            ),
            "audits": int(r["audits"]),
            "diverged": int(r["diverged"]),
        })
    t0, t1 = state.get("t_first"), state.get("t_last")
    wall_s = round(float(t1) - float(t0), 3) if (
        isinstance(t0, (int, float)) and isinstance(t1, (int, float))
    ) else 0.0
    return {
        "schema_version": SCHEMA_VERSION,
        "wall_s": wall_s,
        "events": int(state.get("events", 0)),
        "tenants": rows,
        "totals": {
            "tenants": len(rows),
            "arrivals": int(totals["arrivals"]),
            "admitted": int(totals["admitted"]),
            "completed": int(totals["completed"]),
            "sheds": int(totals["sheds"]),
            "tokens": int(totals["tokens"]),
            "chip_s": round(totals["chip_s"], 6),
            "cost_usd": round(totals["cost_usd"], 6),
            "audits": int(totals["audits"]),
            "diverged": int(totals["diverged"]),
        },
    }
