"""SLO engine: error budgets + multi-window burn-rate alerting.

PR 2 produced the raw signals (request/TTFT histograms, labeled counters);
this module turns them into *decisions* an operator can page on. The design
follows the multiwindow, multi-burn-rate alerting recipe from the Google SRE
workbook (and the framing NinjaLLM/HA-RAG assume for accelerator fleets:
serving is a latency/cost-budget problem, so the budget must be a live,
computable object):

- an :class:`SloSpec` declares one objective over an SLI stream —
  ``latency`` (good event = request faster than ``threshold_s``, read off a
  registry histogram's fixed buckets) or ``availability`` (good event =
  non-5xx request, read off the ``rag_http_requests_total{route,code}``
  family the server maintains);
- the engine samples the CUMULATIVE (good, total) pair per SLI into a
  time-indexed ring and evaluates windowed SLI values by differencing the
  ring — the same trick bench.py uses to take per-pass quantiles from
  cumulative histograms, applied over wall-clock windows;
- **burn rate** per window = (bad fraction) / (1 - objective): burn 1.0
  spends exactly the error budget by the end of the SLO period, 14.4 spends
  a 30-day budget in 2 days. The alert signal pairs a long window with a
  short one and fires only when BOTH burn (long = real spend, short = still
  happening now): fast pair 5m/1h at 14.4 → page; slow pair 30m/6h at 6 →
  ticket. A calm slow pair during a fast-pair page means "new and sharp",
  both pairs firing means "sustained" — the distinction §RUNBOOK documents;
- everything is re-exported as ``rag_slo_*`` callback gauges so the SAME
  numbers land in the Prometheus scrape, and ``GET /slo`` returns the full
  report as JSON for humans and runbooks.

Windows are wall-clock and the sampler is *pull-lazy*: every evaluation
records a fresh ring sample first, so a scrape cadence of 10-60 s gives the
windows their resolution with no background thread to leak. ``clock`` is
injectable, which is how tests/test_slo.py replays hours of traffic in
microseconds against hand-computed burn fixtures.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from rag_llm_k8s_tpu.core.config import SloConfig
from rag_llm_k8s_tpu.obs import metrics as obs_metrics

__all__ = ["SloSpec", "SloEngine", "BurnPolicy", "default_specs"]


# (short_s, long_s, threshold): fire when BOTH windows burn >= threshold.
# The canonical SRE-workbook pairs for a 30-day budget: 14.4 = 2% of budget
# in 1h (page), 6 = 10% of budget in 6h (ticket).
@dataclass(frozen=True)
class BurnPolicy:
    fast_short_s: float = 300.0
    fast_long_s: float = 3600.0
    fast_threshold: float = 14.4
    slow_short_s: float = 1800.0
    slow_long_s: float = 21600.0
    slow_threshold: float = 6.0

    def windows(self) -> Tuple[float, ...]:
        return tuple(sorted({self.fast_short_s, self.fast_long_s,
                             self.slow_short_s, self.slow_long_s}))


@dataclass(frozen=True)
class SloSpec:
    """One declarative objective over a served SLI stream.

    ``kind='latency'``: good = observation <= ``threshold_s``, counted from
    the named histogram's cumulative buckets (the threshold is snapped to
    the nearest bucket bound at evaluation — log-spaced ladders keep that
    snap within ~12% on the request ladder, and the snapped value is
    reported so dashboards show the real boundary).

    ``kind='availability'``: good = sample with a non-5xx ``code`` label,
    counted from the named labeled-counter family.

    ``labels`` (optional) restricts the SLI stream to family children whose
    label set CONTAINS every (name, value) pair — the mechanism per-tenant
    objectives use: the same family, one tenant's slice of it.
    """

    name: str
    kind: str  # 'latency' | 'availability'
    metric: str  # histogram family (latency) / counter family (availability)
    objective: float  # fraction of good events, e.g. 0.95
    threshold_s: Optional[float] = None  # latency only
    policy: BurnPolicy = field(default_factory=BurnPolicy)
    labels: Optional[Tuple[Tuple[str, str], ...]] = None  # child filter

    def __post_init__(self):
        if self.kind not in ("latency", "availability"):
            raise ValueError(f"SloSpec.kind={self.kind!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        if self.kind == "latency" and not self.threshold_s:
            raise ValueError("latency SLO needs threshold_s")


def default_specs(cfg: Optional[SloConfig] = None) -> List[SloSpec]:
    """The served defaults (knobs on ``core/config.py::SloConfig`` — env
    ``TPU_RAG_SLO_*``, parsed there with safe fallbacks so a malformed
    value retunes to the default instead of raising at scrape time):

    - availability 99.9% of requests non-5xx;
    - request p95 < 2 s (the BASELINE.md north-star budget applied at p95 —
      ``TPU_RAG_SLO_REQUEST_P95_S`` / ``_OBJECTIVE`` to retune);
    - TTFT p95 < 1 s (meaningful under continuous serving, where TTFT is
      measured exactly; vacuously compliant when the histogram is empty);
    - quality p99 logit err ≤ 0.15: of the shadow auditor's audited
      requests (obs/shadow.py — every audit observes its measured
      exact-vs-delivered logit error into ``rag_quality_logit_err``, 0.0
      when the streams matched), 99% must stay under the pinned
      approximation tolerance. The SLI is dimensionless (a logit gap, not
      seconds) but the windowed-burn machinery is identical — the
      ``threshold_s`` field carries the logit bound. Vacuously compliant
      while the auditor is off or nothing was audited.
    """
    if cfg is None:
        cfg = SloConfig.from_env()
    return [
        SloSpec("availability", "availability", "rag_http_requests_total",
                objective=cfg.availability_objective),
        SloSpec("request_p95", "latency", "rag_request_duration_seconds",
                objective=cfg.request_p95_objective,
                threshold_s=cfg.request_p95_s),
        SloSpec("ttft_p95", "latency", "rag_time_to_first_token_seconds",
                objective=cfg.ttft_p95_objective,
                threshold_s=cfg.ttft_p95_s),
        SloSpec("quality_p99_logit_err", "latency", "rag_quality_logit_err",
                objective=cfg.quality_objective,
                threshold_s=cfg.quality_logit_err),
    ]


class SloEngine:
    """Windows the registry's cumulative state into burn rates.

    ``evaluate()`` is the one entry point: it appends a fresh ring sample
    (pruning past the longest window) and returns the per-SLO report. The
    gauges and ``GET /slo`` both go through a short evaluation cache
    (``min_eval_interval_s``) so a scrape reading five ``rag_slo_*``
    families computes the report once, not five times.
    """

    def __init__(
        self,
        registry: obs_metrics.MetricsRegistry,
        specs: Optional[List[SloSpec]] = None,
        clock: Callable[[], float] = time.monotonic,
        min_eval_interval_s: float = 1.0,
        register_gauges: bool = True,
    ):
        self.registry = registry
        self.specs = list(specs) if specs is not None else default_specs()
        names = [s.name for s in self.specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.clock = clock
        self.min_eval_interval_s = min_eval_interval_s
        self._lock = threading.Lock()
        # ring: per spec, [(t, good_cum, total_cum)]
        self._ring: Dict[str, List[Tuple[float, float, float]]] = {
            s.name: [] for s in self.specs
        }
        # per-tracked-tenant objectives (ISSUE 18): tenant -> derived specs.
        # Reconciled by set_tenants() against the edge's TenantTracker, so
        # the set is bounded by the tracker's top_k by construction. These
        # feed the report's "tenants" section only — no per-tenant gauges,
        # and no vote in the global page/ticket signal (one tenant's burn
        # is an attribution fact, not a fleet page).
        self._tenant_specs: Dict[str, List[SloSpec]] = {}
        self._horizon = max(
            max(s.policy.windows()) for s in self.specs
        ) if self.specs else 0.0
        self._cached: Optional[Dict] = None
        self._cached_at: float = -float("inf")
        if register_gauges:
            self._register_gauges()

    # -- cumulative SLI reads -------------------------------------------
    @staticmethod
    def _match(spec: SloSpec, labels) -> bool:
        """Does a family child belong to this spec's SLI stream?"""
        if spec.labels is None:
            return True
        have = dict(labels)
        return all(have.get(k) == v for k, v in spec.labels)

    def _cumulative(self, spec: SloSpec) -> Tuple[float, float]:
        """(good, total) lifetime counts for one spec, straight off the
        registry. Missing families read as (0, 0) — no traffic yet."""
        fam = self.registry.get_family(spec.metric)
        if fam is None:
            return 0.0, 0.0
        if spec.kind == "availability":
            good = total = 0.0
            for labels, child in fam.items():
                if not self._match(spec, labels):
                    continue
                v = child.value
                total += v
                code = dict(labels).get("code", "")
                if not code.startswith("5"):
                    good += v
            return good, total
        # latency: cumulative count at the bucket bound covering threshold
        good = total = 0.0
        for labels, child in fam.items():
            if not self._match(spec, labels):
                continue
            counts, _, count = child.snapshot()
            total += count
            # observe() uses bisect_left(bounds, v): every observation
            # <= bounds[i] lands in counts[:i+1] — mirror that here so
            # "good" counts exactly the observations a cold observe at
            # the threshold value would join. CLAMPED below the +Inf
            # overflow slot: a threshold above the ladder's top bound must
            # evaluate at the top bound (snapped_threshold reports it), not
            # count the overflow as "good" and go vacuously compliant.
            i = min(bisect_left(child.bounds, spec.threshold_s),
                    len(child.bounds) - 1)
            good += sum(counts[: i + 1])
        return good, total

    def snapped_threshold(self, spec: SloSpec) -> Optional[float]:
        """The bucket bound the threshold actually evaluates at."""
        if spec.kind != "latency":
            return None
        fam = self.registry.get_family(spec.metric)
        if fam is None:
            return spec.threshold_s
        for _, child in fam.items():
            i = bisect_left(child.bounds, spec.threshold_s)
            return float(child.bounds[min(i, len(child.bounds) - 1)])
        return spec.threshold_s

    # -- sampling ring ---------------------------------------------------
    def sample(self, now: Optional[float] = None) -> None:
        """Record one cumulative sample per spec (and prune the ring)."""
        t = self.clock() if now is None else now
        with self._lock:
            specs = list(self.specs)
            for tenant_specs in self._tenant_specs.values():
                specs.extend(tenant_specs)
            for spec in specs:
                good, total = self._cumulative(spec)
                ring = self._ring.setdefault(spec.name, [])
                if ring and ring[-1][0] >= t:
                    # monotonic guard: a same-instant re-sample replaces
                    ring.pop()
                ring.append((t, good, total))
                cutoff = t - self._horizon - 1.0
                while len(ring) > 2 and ring[1][0] <= cutoff:
                    ring.pop(0)

    def _window_rate(self, name: str, window_s: float, now: float
                     ) -> Tuple[float, float, float]:
        """(bad_fraction, good, total) over the trailing window.

        The baseline sample is the newest one at or before ``now - window``;
        when monitoring began INSIDE the window (no sample that old yet),
        the baseline is zero — the window counts everything since counter
        start, the standard cold-start behavior, so burn is computable from
        the first minute of traffic. Zero in-window traffic reads as
        (0.0, 0, 0): no events, no burn.
        """
        ring = self._ring.get(name)
        if not ring:
            return 0.0, 0.0, 0.0
        t0 = now - window_s
        base: Optional[Tuple[float, float, float]] = None
        for s in ring:
            if s[0] <= t0:
                base = s
            else:
                break
        if base is None:
            base = (t0, 0.0, 0.0)
        head = ring[-1]
        good = head[1] - base[1]
        total = head[2] - base[2]
        if total <= 0:
            return 0.0, 0.0, 0.0
        bad_frac = max(0.0, min(1.0, 1.0 - good / total))
        return bad_frac, good, total

    # -- evaluation ------------------------------------------------------
    def evaluate(self, force: bool = False) -> Dict:
        """Sample + compute the full report (cached ``min_eval_interval_s``).

        Report shape (per SLO): windowed burn rates keyed "5m"/"1h"/...,
        ``fast_burn``/``slow_burn`` booleans (both-windows rule),
        ``error_budget_remaining`` over the slow long window (1.0 = budget
        untouched, 0.0 = fully spent, floored at 0), and ``compliant`` =
        the long-window SLI meets the objective.
        """
        now = self.clock()
        with self._lock:
            if (not force and self._cached is not None
                    and now - self._cached_at < self.min_eval_interval_s):
                return self._cached
            tenant_specs = {
                t: list(ss) for t, ss in sorted(self._tenant_specs.items())
            }
        self.sample(now)
        slos = []
        any_page = any_ticket = False
        for spec in self.specs:
            entry = self._spec_entry(spec, now)
            slos.append(entry)
            any_page = any_page or entry["fast_burn"]
            any_ticket = any_ticket or entry["slow_burn"]
        # per-tenant burn (attribution, not paging: a single tenant's burn
        # names WHO is spending the budget — the fleet page stays with the
        # aggregate specs above)
        tenants = {
            t: [self._spec_entry(s, now) for s in ss]
            for t, ss in tenant_specs.items()
        }
        report = {
            "slos": slos, "page": any_page, "ticket": any_ticket,
            "tenants": tenants,
        }
        with self._lock:
            self._cached = report
            self._cached_at = now
        return report

    def _spec_entry(self, spec: SloSpec, now: float) -> Dict:
        """The per-SLO report entry — shared by the aggregate and the
        per-tenant loops so the two sections can never disagree on math."""
        pol = spec.policy
        budget = 1.0 - spec.objective
        burn: Dict[str, float] = {}
        frac_by_w: Dict[float, float] = {}
        totals: Dict[float, float] = {}
        with self._lock:  # consistent ring view vs a concurrent sample()
            for w in pol.windows():
                bad_frac, _, total = self._window_rate(spec.name, w, now)
                frac_by_w[w] = bad_frac
                totals[w] = total
                burn[_fmt_window(w)] = round(bad_frac / budget, 3)
        fast = (frac_by_w[pol.fast_short_s] / budget >= pol.fast_threshold
                and frac_by_w[pol.fast_long_s] / budget >= pol.fast_threshold)
        slow = (frac_by_w[pol.slow_short_s] / budget >= pol.slow_threshold
                and frac_by_w[pol.slow_long_s] / budget >= pol.slow_threshold)
        long_frac = frac_by_w[pol.slow_long_s]
        remaining = max(0.0, 1.0 - long_frac / budget)
        entry = {
            "name": spec.name,
            "kind": spec.kind,
            "metric": spec.metric,
            "objective": spec.objective,
            "burn_rate": burn,
            "fast_burn": fast,
            "slow_burn": slow,
            "error_budget_remaining": round(remaining, 4),
            "compliant": long_frac <= budget,
            "window_events": {
                _fmt_window(w): int(t) for w, t in totals.items()
            },
        }
        if spec.kind == "latency":
            entry["threshold_s"] = spec.threshold_s
            entry["threshold_bucket_s"] = self.snapped_threshold(spec)
        return entry

    # -- per-tenant objectives (ISSUE 18) --------------------------------
    def _make_tenant_specs(self, tenant: str) -> List[SloSpec]:
        """Derive one availability + one latency objective for a tenant
        from the aggregate specs, re-pointed at the ``rag_tenant_*``
        families and filtered to that tenant's children — objectives and
        policies stay single-sourced from SloConfig."""
        base = {s.name: s for s in self.specs}
        out: List[SloSpec] = []
        avail = base.get("availability")
        if avail is not None:
            out.append(SloSpec(
                f"tenant:{tenant}:availability", "availability",
                "rag_tenant_http_requests_total",
                objective=avail.objective, policy=avail.policy,
                labels=(("tenant", tenant),),
            ))
        lat = base.get("request_p95")
        if lat is not None:
            out.append(SloSpec(
                f"tenant:{tenant}:request_p95", "latency",
                "rag_tenant_request_seconds",
                objective=lat.objective, threshold_s=lat.threshold_s,
                policy=lat.policy, labels=(("tenant", tenant),),
            ))
        return out

    def set_tenants(self, tenants) -> None:
        """Reconcile the per-tenant spec set against the tracker's tracked
        tenants (called from the scrape/evaluate path). A departed tenant's
        ring is dropped; a newly tracked tenant starts cold — windowed burn
        becomes meaningful from its first minute of samples, the same
        cold-start rule the aggregate specs follow."""
        want = sorted({str(t) for t in tenants if t})
        with self._lock:
            if want == sorted(self._tenant_specs):
                return
            for t in list(self._tenant_specs):
                if t not in want:
                    for s in self._tenant_specs.pop(t):
                        self._ring.pop(s.name, None)
            for t in want:
                if t not in self._tenant_specs:
                    self._tenant_specs[t] = self._make_tenant_specs(t)
            self._cached = None  # the report's tenant section changed shape

    # -- gauge export ----------------------------------------------------
    def _register_gauges(self) -> None:
        """`rag_slo_*` families: the report's numbers as callback gauges, so
        the alerting math ships in the same scrape the SLIs do (a Prometheus
        can alert on our burn rates OR recompute its own from the buckets —
        both read one registry)."""
        reg = self.registry
        burn_fam = reg.labeled_gauge(
            "rag_slo_burn_rate",
            "windowed error-budget burn rate (1.0 spends the budget exactly "
            "over the SLO period); slo + window labels",
        )
        budget_fam = reg.labeled_gauge(
            "rag_slo_error_budget_remaining",
            "fraction of error budget left over the slow long window",
        )
        compliant_fam = reg.labeled_gauge(
            "rag_slo_compliant", "1 when the long-window SLI meets the objective"
        )
        fast_fam = reg.labeled_gauge(
            "rag_slo_fast_burn_active",
            "1 when both fast windows burn over threshold (page)",
        )
        slow_fam = reg.labeled_gauge(
            "rag_slo_slow_burn_active",
            "1 when both slow windows burn over threshold (ticket)",
        )

        def _entry(name: str) -> Dict:
            for e in self.evaluate()["slos"]:
                if e["name"] == name:
                    return e
            return {}

        for spec in self.specs:
            nm = spec.name
            for w in spec.policy.windows():
                wl = _fmt_window(w)
                burn_fam.labels_callback(
                    lambda nm=nm, wl=wl: _entry(nm).get("burn_rate", {}).get(wl, 0.0),
                    slo=nm, window=wl,
                )
            budget_fam.labels_callback(
                lambda nm=nm: _entry(nm).get("error_budget_remaining", 1.0), slo=nm
            )
            compliant_fam.labels_callback(
                lambda nm=nm: float(_entry(nm).get("compliant", True)), slo=nm
            )
            fast_fam.labels_callback(
                lambda nm=nm: float(_entry(nm).get("fast_burn", False)), slo=nm
            )
            slow_fam.labels_callback(
                lambda nm=nm: float(_entry(nm).get("slow_burn", False)), slo=nm
            )


def _fmt_window(seconds: float) -> str:
    s = int(seconds)
    if s % 3600 == 0:
        return f"{s // 3600}h"
    if s % 60 == 0:
        return f"{s // 60}m"
    return f"{s}s"
