"""Engine flight recorder: a causal event journal for the serving substrate.

PR 2 made the stack report *what* is happening (metrics/traces), PR 3 *when
to care* (SLO burn rates), PR 4 how to *act under stress* (shed, evict,
reset, resubmit). What was still invisible is *why*: when the breaker flips
or a reset storm hits, the causal sequence of scheduler decisions — admit →
preempt → retier → swap-in → resubmit — exists only as counters that move
in aggregate. This module is the journal those decisions write to:

- :data:`EVENTS` — the CLOSED catalog of typed event names (same contract
  as ``resilience/faults.SITES``: a typo'd event name is a programming
  error, not a silently-empty timeline). Every decision point in the
  serving substrate calls ``flight.emit("<type>", ...)``; ragcheck's
  EVENT-REGISTRY rule pins emit sites ↔ catalog ↔ docs three ways.
- :class:`FlightRecorder` — a fixed-size ring of monotonic-stamped events.
  One append under one tiny lock, never any device work; the hot decode
  path pays ~a microsecond per sync window (the ``flight_overhead`` bench
  leg holds the recorder to ≤ 2% of B=8 decode steps/s). On by default.
- **timeline reconstruction** — events carry the scheduler request id, so
  ``timeline(rid)`` returns one request's ordered event chain with
  inter-event deltas (``GET /debug/timeline/<id>``; ``{"timeline": true}``
  on ``/generate`` opts the response in).
- :class:`IncidentSpooler` — trigger-driven post-mortem bundles: breaker
  flip, reset storm, pool-exhaustion shed, and deadline expiry snapshot
  the recent journal + the metrics registry + a config fingerprint + the
  trace ring into ONE self-contained JSON file on a bounded on-disk spool
  (``GET /debug/incidents``), so reconstructing an incident needs no live
  pod. ``scripts/flightview.py`` renders a bundle offline.
- :class:`FlightWAL` — the DURABLE tee: every emitted event also lands on
  disk as one fsynced JSON line in a bounded, segment-rotated,
  epoch-per-incarnation journal. The ring explains a live process; the
  WAL explains a dead one — a warm restart (server/main.py) scans it,
  finds requests with an ``arrival`` but no terminal event, and resumes
  them through the scheduler's fold path. All spool/WAL file writes share
  :func:`durable_write`'s tmp-fsync-rename discipline (ragcheck
  DURABLE-WRITE pins this).

The journal is a STABLE CONTRACT: every event and bundle carries
:data:`SCHEMA_VERSION`, bumped whenever an event's meaning or a bundle
field changes shape (docs/OBSERVABILITY.md documents both).

Configuration comes through ``core/config.py::FlightConfig`` (env
``TPU_RAG_FLIGHT*``) — this module reads no environment itself.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional

__all__ = [
    "EVENTS",
    "SCHEMA_VERSION",
    "FlightRecorder",
    "FlightWAL",
    "IncidentSpooler",
    "arrival_ids",
    "config_fingerprint",
    "configure",
    "durable_write",
    "emit",
    "export_journal",
    "load_journal",
    "recorder",
    "scan_wal",
    "stream_hash",
    "wal_enabled",
]

logger = logging.getLogger(__name__)

#: Journal/bundle schema version. Bump when an event's attrs change
#: meaning or a bundle field changes shape; flightview refuses newer
#: schemas it does not know.
SCHEMA_VERSION = 1

# The closed event catalog: name -> what the event records. Every entry is
# emitted by >= 1 call site in the package and documented in
# docs/OBSERVABILITY.md (ragcheck EVENT-REGISTRY enforces all three ways).
EVENTS: Dict[str, str] = {
    # -- continuous engine / scheduler (engine/continuous.py) ------------
    "arrival": "request submitted to the scheduler (prompt_len, max_new; "
               "seed/deadline_ms when set; prompt token ids while the "
               "arrival_ids knob is on) — the replay trace record "
               "sim/replay.py re-drives a journal from",
    "admit": "request admitted into a decode slot (slot, prompt_len, "
             "bucket, tok0; prefixed admissions add prefix_len/shared)",
    "sync_window_open": "decode sync window dispatched (steps, active rows)",
    "sync_window_close": "decode sync window drained (steps, rows done, "
                         "duration_ms)",
    "eos": "row finished decoding (reason: eos | budget; n_tokens)",
    "preempt": "row preempted mid-decode by pool exhaustion (blocks "
               "returned); the scheduler resubmits it",
    "evict": "row evicted mid-decode (deadline expiry / caller gone)",
    "block_grow": "row's block table grown ahead of a sync window "
                  "(blocks added, total mapped)",
    "reset": "engine device state rebuilt after a failed step/insert "
             "(every in-flight slot wiped)",
    "resubmit": "in-flight request re-queued after a reset, preemption, or "
                "warm restart (outcome: resubmitted | preempt_resume | "
                "gave_up | restored; n_emitted tokens carried over)",
    "complete": "request delivered (n_tokens, stream_fnv — FNV-1a over "
                "the emitted token stream, the byte-consistency anchor)",
    "token_emit": "a row's emitted-token delta journaled at a sync-window "
                  "drain while the flight WAL is on (toks — the tokens "
                  "appended since the row's last watermark); concatenating "
                  "a request's token_emit events in seq order rebuilds its "
                  "full emitted stream, the state a warm restart resumes "
                  "from",
    "spec_draft": "a speculative sync window drafted continuations by "
                  "prompt-lookup over each row's history (rows drafting, "
                  "active rows, drafted tokens total)",
    "spec_verify": "a multi-token verify step judged its window's drafts "
                   "(drafted, accepted, rejected, emitted token counts — "
                   "accepted/drafted is the window's acceptance rate)",
    "goodput_window": "one device sync window's goodput attribution "
                      "(obs/goodput.py): kind, dur_ms, active requests, "
                      "per-category chip-ms (summing to dur_ms — the "
                      "conservation invariant), tokens, per-window "
                      "mfu/bw/bound — flightview --goodput rebuilds the "
                      "/debug/goodput report from these offline",
    "window_budget": "a unified ragged sync window split its token budget "
                     "(budget, decode_lanes, chunk_tokens scheduled, "
                     "chunks, queued admissions still pending)",
    "prefill_chunk_sched": "the window planner scheduled one admission's "
                           "prefill chunk (offset into the prompt, tokens "
                           "fed, remaining after, final=1 samples tok0)",
    # -- KV block pool (engine/kv_pool.py) -------------------------------
    "pool_alloc": "physical KV blocks taken from the pool (blocks, free "
                  "remaining)",
    "pool_free": "physical KV blocks returned to the pool (blocks, free)",
    "pool_exhausted": "an allocation the pool could not serve (requested, "
                      "free) — backpressure, not failure",
    # -- prefix cache + tiering (engine/prefix_cache.py, engine/tiering.py)
    "prefix_hit": "segment KV served from the prefix cache (segments, "
                  "tokens; memo=1 when the whole assembled chain hit)",
    "prefix_miss": "segment KV built fresh on the resolve path (segments, "
                   "tokens prefilled)",
    "retier": "a tier-maintenance sweep moved entries between hotness "
              "tiers (moved)",
    "swap_in": "cold-tier chunk KV swapped host→HBM (trigger: lookahead — "
               "prefetched off the critical path; demand — on a serving "
               "tail)",
    "swap_in_fallback": "a failed swap-in fell back to "
                        "recompute-from-tokens (host buffer released)",
    "chunk_splice": "a hot chunk's canonical KV spliced at an arbitrary "
                    "prompt position (chunk-granular reuse; tokens, delta; "
                    "pool=1 when assembled straight into pool blocks)",
    "rerotate": "cached K planes position-shifted by the closed-form RoPE "
                "delta rotation (tokens, delta) — no re-prefill",
    "boundary_fixup": "a spliced chunk's first tokens re-prefilled with "
                      "the true left context (tokens) — the bounded "
                      "boundary-correction pass",
    "host_spill_evict": "the host spill store's byte budget evicted a "
                        "cold chunk's backing (bytes)",
    # -- retrieval lookahead (rag/lookahead.py) --------------------------
    "lookahead_launch": "retrieval launched ahead of need (trigger: "
                        "admission | session)",
    "lookahead_join": "serving tail joined its retrieval (outcome: hit | "
                      "late | miss)",
    "lookahead_waste": "a lookahead retrieval died unconsumed (reason: "
                       "superseded | expired | abandoned | stale | failed)",
    "prestage": "a resolved retrieval's chunk KV pre-staged ahead of "
                "admission (prefix-cache entries / pool registration)",
    # -- shadow quality auditor (obs/shadow.py) --------------------------
    "shadow_audit": "one sampled request's shadow audit finished (outcome: "
                    "clean | diverged | skipped | failed; n tokens "
                    "compared, err — the minimal explaining logit "
                    "perturbation, pos — first divergence, approx — the "
                    "request's approximation fingerprint, reason on "
                    "skips). flightview --quality rebuilds the "
                    "/debug/quality report from these offline",
    "quality_divergence": "a shadow audit caught the delivered stream "
                          "diverging from the exact path (pos, err, "
                          "approx — the approximations the divergence is "
                          "attributed to); a second one inside the burst "
                          "window spools an incident bundle",
    # -- disaggregated pools + router (engine/continuous.py,
    #    server/router.py) --------------------------------------------------
    "route_decision": "the front-tier router picked replicas for a request "
                      "(prefill/decode targets, mode: disagg | unified, "
                      "affinity score and affinity_hit, candidates "
                      "considered) — flightview --router aggregates these "
                      "into the affinity hit rate",
    "migrate_begin": "a prefill-role engine exported a request's pool "
                     "blocks for hand-off to a decode-role engine (blocks, "
                     "kv_len; every exported block is released on the "
                     "prefill side before the event returns)",
    "migrate_done": "a decode-role engine imported a migrated request into "
                    "a fresh row (slot, blocks, kv_len) — decode continues "
                    "the same (seed, position) sampling sequence, so the "
                    "stream is byte-identical to a unified run",
    # -- resilience (resilience/) ----------------------------------------
    "shed": "request rejected at the admission gate (reason, status)",
    "deadline": "a request's end-to-end deadline expired (stage)",
    "breaker_open": "the engine-reset circuit breaker flipped open "
                    "(resets in window) — readiness goes 503",
    "drain": "the lifecycle coordinator changed drain phase (phase: begin "
             "| timeout | complete; reason on begin, in_flight counts) — "
             "the graceful-shutdown state machine's journal trail",
    "restore": "a warm restart acted on a prior incarnation's WAL (phase: "
               "resume — one in-flight request resubmitted with orig_rid/"
               "n_emitted; rehydrate — warmth-manifest chunks re-staged; "
               "skip — a request the restart could not resume, with "
               "reason)",
}


def stream_hash(tokens: Iterable[int]) -> int:
    """FNV-1a (64-bit) over a token stream — the cheap content identity a
    ``complete`` event records so a timeline can be checked byte-consistent
    against the stream the client actually received."""
    h = 0xCBF29CE484222325
    for t in tokens:
        h ^= int(t) & 0xFFFFFFFFFFFFFFFF
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


class FlightRecorder:
    """Bounded in-process event journal.

    A fixed-size ring of ``(seq, t_monotonic, type, request_id, attrs)``
    tuples. ``emit`` takes ONE tiny lock to claim a slot and write the
    tuple — no allocation beyond the tuple/attrs the caller already built,
    no device work, no I/O — so it is safe at every decision point
    including the per-window decode path. Readers (``snapshot`` /
    ``timeline``) copy the ring under the same lock; events are immutable
    tuples, so a snapshot is always internally consistent.
    """

    def __init__(self, capacity: int = 4096, enabled: bool = True,
                 arrival_ids: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity={capacity}: expected >= 1")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        # whether ``arrival`` events carry the prompt token ids (the
        # exact-replay trace record); off, they keep prompt_len only —
        # the journal stays sized in events, not prompt tokens
        self.arrival_ids = bool(arrival_ids)
        # durable tee: a FlightWAL every emitted event is also appended to
        # (crash-consistent; the warm-restart substrate). None = ring only.
        self.wal: Optional["FlightWAL"] = None
        self._lock = threading.Lock()
        self._buf: List[Optional[tuple]] = [None] * self.capacity
        self._next = 0  # total events ever emitted (seq of the next event)

    # -- write -----------------------------------------------------------
    def emit(self, etype: str, request_id: Optional[int] = None,
             **attrs) -> None:
        """Append one event. Unknown event types raise — the catalog is
        closed (a typo'd type would journal nothing, silently)."""
        if not self.enabled:
            return
        if etype not in EVENTS:
            raise ValueError(
                f"unknown flight event {etype!r}; the catalog is "
                f"flight.EVENTS"
            )
        ev = (0, time.monotonic(), etype, request_id, attrs)
        with self._lock:
            seq = self._next
            self._next = seq + 1
            # the seq is stamped under the lock so journal order and slot
            # claim agree even across producers
            self._buf[seq % self.capacity] = (seq,) + ev[1:]
        wal = self.wal
        if wal is not None:
            d = {"seq": seq, "t": round(ev[1], 6), "type": etype}
            if request_id is not None:
                d["rid"] = request_id
            if attrs:
                d.update(attrs)
            wal.append(d)

    # -- read ------------------------------------------------------------
    @property
    def events_emitted(self) -> int:
        with self._lock:
            return self._next

    def _events_locked(self) -> List[tuple]:
        live = [e for e in self._buf if e is not None]
        live.sort(key=lambda e: e[0])
        return live

    def snapshot(self, request_id: Optional[int] = None,
                 etype: Optional[str] = None) -> List[Dict]:
        """The journal's surviving events, oldest first, as JSON-ready
        dicts (the incident bundle's ``journal`` field)."""
        with self._lock:
            live = self._events_locked()
        out = []
        for seq, t, typ, rid, attrs in live:
            if request_id is not None and rid != request_id:
                continue
            if etype is not None and typ != etype:
                continue
            d = {"seq": seq, "t": round(t, 6), "type": typ}
            if rid is not None:
                d["rid"] = rid
            if attrs:
                d.update(attrs)
            out.append(d)
        return out

    def timeline(self, request_id: int) -> Dict:
        """One request's ordered event chain with inter-event deltas —
        the ``GET /debug/timeline/<id>`` / ``{"timeline": true}`` payload.
        Times are relative to the request's first surviving event."""
        evs = self.snapshot(request_id=request_id)
        t0 = evs[0]["t"] if evs else 0.0
        prev = t0
        out = []
        for e in evs:
            t = e.pop("t")
            e["t_ms"] = round((t - t0) * 1e3, 3)
            e["dt_ms"] = round((t - prev) * 1e3, 3)
            prev = t
            e.pop("rid", None)  # redundant inside a per-request timeline
            out.append(e)
        return {
            "schema_version": SCHEMA_VERSION,
            "request_id": request_id,
            "events": out,
        }

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self.capacity
            self._next = 0


# the process recorder: decision points across the package write here via
# the module-level ``emit`` (the same singleton pattern as faults.py — the
# journal must see every layer's events in ONE causal order, and engines
# are constructed long before any service exists to hand them a handle)
_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


_UNSET = object()


def configure(enabled: Optional[bool] = None,
              capacity: Optional[int] = None,
              arrival_ids: Optional[bool] = None,
              wal=_UNSET) -> FlightRecorder:
    """Apply ``FlightConfig`` to the process recorder (the service calls
    this at construction; bench legs toggle ``enabled`` directly). A
    capacity change rebuilds the ring (journal starts fresh); an
    enabled-only change keeps it. ``wal`` attaches (a :class:`FlightWAL`)
    or detaches (None) the durable tee; omitted, the current tee stays."""
    global _RECORDER
    if capacity is not None and int(capacity) != _RECORDER.capacity:
        old = _RECORDER
        _RECORDER = FlightRecorder(
            int(capacity),
            old.enabled if enabled is None else bool(enabled),
            old.arrival_ids if arrival_ids is None else bool(arrival_ids),
        )
        _RECORDER.wal = old.wal
    elif enabled is not None:
        _RECORDER.enabled = bool(enabled)
    if arrival_ids is not None:
        _RECORDER.arrival_ids = bool(arrival_ids)
    if wal is not _UNSET:
        _RECORDER.wal = wal
    return _RECORDER


def emit(etype: str, request_id: Optional[int] = None, **attrs) -> None:
    """The one instrumentation entry point: append ``etype`` to the
    process journal (free when the recorder is disabled)."""
    rec = _RECORDER
    if not rec.enabled:
        return
    rec.emit(etype, request_id, **attrs)


def arrival_ids() -> bool:
    """Whether ``arrival`` events should carry prompt token ids — read at
    the emit site (engine/continuous.py submit); False when the recorder
    is disabled outright, so callers need not re-check ``enabled``."""
    rec = _RECORDER
    return rec.enabled and rec.arrival_ids


def wal_enabled() -> bool:
    """Whether emitted events reach a durable WAL — the gate the engine's
    ``token_emit`` journaling checks per sync window, so the extra
    per-window emit (and its fsync) costs nothing when no WAL is
    attached."""
    rec = _RECORDER
    return rec.enabled and rec.wal is not None


# ---------------------------------------------------------------------------
# journal export / ingest (the replay harness's file format)
# ---------------------------------------------------------------------------


def export_journal(path: str, events: Optional[List[Dict]] = None,
                   meta: Optional[Dict] = None) -> Dict:
    """Write the process journal (or an explicit ``events`` list — e.g. a
    simulator's synthetic journal) as a flightview-loadable JSON bundle:
    ``{"schema_version", "journal", ...meta}``. Returns the bundle."""
    bundle: Dict = {
        "schema_version": SCHEMA_VERSION,
        "journal": _RECORDER.snapshot() if events is None else list(events),
    }
    if meta:
        for k, v in meta.items():
            bundle.setdefault(k, v)
    durable_write(path, bundle)
    return bundle


def load_journal(path: str) -> List[Dict]:
    """Read a journal written by ``export_journal`` (or a spooled incident
    bundle, or a bare event list) back to its event list. A NEWER schema
    loads with a warning — the replay parser (sim/replay.py) skips event
    types it does not know, so a best-effort read beats a refusal here;
    flightview keeps its own stricter gate for rendering."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):
        return doc
    ver = doc.get("schema_version")
    if ver is not None and int(ver) > SCHEMA_VERSION:
        logger.warning(
            "journal %s has schema_version %s (this build knows %s); "
            "unknown event types will be skipped", path, ver, SCHEMA_VERSION,
        )
    journal = doc.get("journal")
    if not isinstance(journal, list):
        raise ValueError(f"{path}: no 'journal' event list in bundle")
    return journal


# ---------------------------------------------------------------------------
# durable writes + the flight WAL
# ---------------------------------------------------------------------------


def durable_write(path: str, obj: Dict) -> None:
    """THE crash-consistent JSON write: tmp file → flush → fsync →
    ``os.replace`` → directory fsync. A reader never sees a torn or empty
    file — it sees the old content or the new content, even across
    SIGKILL/power loss. Every spool/WAL-adjacent write in this module and
    ``resilience/lifecycle.py`` goes through here (ragcheck DURABLE-WRITE
    mechanizes that), so the discipline cannot quietly regress one call
    site at a time."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, separators=(",", ":"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # fsync the directory so the rename itself survives a crash — without
    # it the data is durable but the NAME may not be
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


class FlightWAL:
    """Bounded, segment-rotated write-ahead journal of flight events.

    The ring answers "what just happened" for a LIVE process; the WAL
    answers it for a DEAD one. Attached to the recorder (``configure(wal=
    …)``) it tees every emitted event onto disk as one JSON line, fsynced
    per append, under ``dir/wal_<epoch>_<seg>.jsonl``:

    - **epoch** — one per process incarnation, ``max(existing) + 1`` at
      construction. A restart never appends into a dead incarnation's
      segments, so "what was in flight when we died" stays frozen exactly
      as the crash left it.
    - **segments** — a new file every ``segment_events`` appends; the
      oldest files past ``max_segments`` (across ALL epochs) are pruned.
      The WAL is a bounded flight journal, not an unbounded database.
    - **torn tails** — an append killed mid-write leaves a partial final
      line in one segment; :func:`scan_wal` skips unparseable lines, so a
      SIGKILL costs at most the one event being written.

    Appends take one lock and one fsync — this is the durability tax the
    warm-restart contract pays, measured by the bench ``restart_warmth``
    leg's WAL-on throughput column. A failed append logs and drops the
    event rather than taking the serving path down.
    """

    def __init__(self, dir: str, segment_events: int = 256,
                 max_segments: int = 64):
        if segment_events < 1:
            raise ValueError(
                f"segment_events={segment_events}: expected >= 1")
        if max_segments < 2:
            raise ValueError(f"max_segments={max_segments}: expected >= 2")
        self.dir = dir
        self.segment_events = int(segment_events)
        self.max_segments = int(max_segments)
        os.makedirs(dir, exist_ok=True)
        existing = _wal_segments(dir)
        self.epoch = (max(e for e, _, _ in existing) + 1) if existing else 1
        self._lock = threading.Lock()
        self._seg = 0
        self._file = None
        self._seg_events = 0
        self.appends = 0
        self.dropped = 0

    # -- write -----------------------------------------------------------
    def append(self, event: Dict) -> None:
        """Durably append one event dict (one JSON line + fsync). Never
        raises — WAL trouble (disk full, dir vanished) must not break the
        emit path; dropped appends are counted."""
        try:
            with self._lock:
                if self._file is None or self._seg_events >= self.segment_events:
                    self._rotate_locked()
                self._file.write(
                    json.dumps(event, separators=(",", ":")) + "\n"
                )
                self._file.flush()
                os.fsync(self._file.fileno())
                self._seg_events += 1
                self.appends += 1
        except Exception:  # noqa: BLE001 — durability is best-effort here
            self.dropped += 1
            logger.warning("flight WAL append failed (dir=%s)", self.dir,
                           exc_info=True)

    def _rotate_locked(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
        self._seg += 1
        path = os.path.join(
            self.dir, f"wal_{self.epoch:08d}_{self._seg:06d}.jsonl"
        )
        # append mode: a crashed-then-restarted SAME epoch cannot happen
        # (epochs are unique), but "a" never truncates evidence either way
        self._file = open(path, "a")
        self._seg_events = 0
        self._prune_locked()

    def _prune_locked(self) -> None:
        segs = _wal_segments(self.dir)
        while len(segs) > self.max_segments:
            _e, _s, name = segs.pop(0)  # oldest (names sort by epoch/seg)
            try:
                os.remove(os.path.join(self.dir, name))
            except OSError:
                pass

    def sync(self) -> None:
        """Flush + fsync the open segment (drain's persist step calls this
        before the process exits)."""
        with self._lock:
            if self._file is not None:
                try:
                    self._file.flush()
                    os.fsync(self._file.fileno())
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


def _wal_segments(dir: str) -> List[tuple]:
    """Sorted ``(epoch, seg, filename)`` for every WAL segment in ``dir``
    (malformed names are ignored, not fatal — the dir may be shared)."""
    out = []
    try:
        names = os.listdir(dir)
    except OSError:
        return []
    for n in names:
        if not (n.startswith("wal_") and n.endswith(".jsonl")):
            continue
        parts = n[len("wal_"):-len(".jsonl")].split("_")
        if len(parts) != 2 or not (parts[0].isdigit() and parts[1].isdigit()):
            continue
        out.append((int(parts[0]), int(parts[1]), n))
    out.sort()
    return out


def scan_wal(dir: str) -> Dict[int, List[Dict]]:
    """Read a WAL directory back to ``{epoch: [events]}``, each epoch's
    events in seq order. Unparseable lines (the torn tail a SIGKILL leaves)
    and unreadable segments are skipped — a scan is best-effort archaeology
    over a dead process, never a gate the restart can fail on."""
    epochs: Dict[int, List[Dict]] = {}
    for epoch, _seg, name in _wal_segments(dir):
        try:
            with open(os.path.join(dir, name)) as f:
                raw = f.read()
        except OSError:
            continue
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue  # torn tail (or garbage) — skip, keep scanning
            if isinstance(ev, dict):
                epochs.setdefault(epoch, []).append(ev)
    for evs in epochs.values():
        evs.sort(key=lambda e: e.get("seq", 0))
    return epochs


# ---------------------------------------------------------------------------
# incident bundles
# ---------------------------------------------------------------------------


def config_fingerprint(config) -> Dict:
    """A bundle's config identity: the full (dataclass) config rendered to
    plain JSON types plus a stable sha256 digest — enough to tell "same
    incident, different config" from "same config, new incident" without a
    live pod."""
    try:
        raw = dataclasses.asdict(config)
    except TypeError:
        raw = {"repr": repr(config)}

    def _plain(v):
        if isinstance(v, dict):
            return {str(k): _plain(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [_plain(x) for x in v]
        if isinstance(v, (str, int, float, bool)) or v is None:
            return v
        return repr(v)

    plain = _plain(raw)
    digest = hashlib.sha256(
        json.dumps(plain, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()
    return {"sha256": digest, "config": plain}


#: incident triggers the spooler accepts (closed, like the event catalog)
TRIGGERS = (
    "breaker_open", "reset_storm", "pool_exhausted_shed", "deadline_exceeded",
    "quality_divergence", "drain_timeout",
)


class IncidentSpooler:
    """Bounded on-disk spool of self-contained incident bundles.

    ``trigger(name, context_fn)`` writes ``context_fn()`` + trigger
    metadata as one JSON file (through :func:`durable_write`'s
    tmp-fsync-rename — a bundle is never torn) and prunes the oldest
    files past ``max_bundles``. Per-trigger
    cooldown keeps a storm from writing a bundle per reset: the FIRST
    occurrence captures the journal that explains the rest.

    Thread-safe; ``clock`` is injectable so tests exercise the cooldown
    without sleeping.
    """

    def __init__(self, spool_dir: str, max_bundles: int = 16,
                 cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if max_bundles < 1:
            raise ValueError(f"max_bundles={max_bundles}: expected >= 1")
        self.spool_dir = spool_dir
        self.max_bundles = int(max_bundles)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._last: Dict[str, float] = {}  # trigger -> last write (clock)
        self._seq = 0

    # -- write -----------------------------------------------------------
    def trigger(self, name: str, context_fn: Callable[[], Dict]
                ) -> Optional[str]:
        """Spool one bundle for ``name`` unless it fired inside the
        cooldown. Returns the bundle id, or None when suppressed. A write
        failure logs and returns None — incident capture must never take
        the serving path down with it."""
        if name not in TRIGGERS:
            raise ValueError(
                f"unknown incident trigger {name!r}; triggers: {TRIGGERS}"
            )
        now = self.clock()
        with self._lock:
            last = self._last.get(name)
            if last is not None and now - last < self.cooldown_s:
                return None
            self._last[name] = now
            self._seq += 1
            seq = self._seq
        try:
            bundle = dict(context_fn())
            bundle["schema_version"] = SCHEMA_VERSION
            bundle["trigger"] = name
            bundle["ts"] = time.time()
            bid = f"{int(bundle['ts'] * 1e3):013d}_{seq:04d}_{name}"
            bundle["id"] = bid
            os.makedirs(self.spool_dir, exist_ok=True)
            path = os.path.join(self.spool_dir, f"incident_{bid}.json")
            durable_write(path, bundle)
            self._prune()
            return bid
        except Exception:  # noqa: BLE001 — capture must not fail serving
            logger.exception("incident bundle write failed (trigger=%s)", name)
            with self._lock:
                # a FAILED capture must not burn the cooldown: the next
                # trigger retries (only un-stamp our own attempt — a
                # concurrent success keeps its newer stamp)
                if self._last.get(name) == now:
                    del self._last[name]
            return None

    def _prune(self) -> None:
        files = self._files()
        while len(files) > self.max_bundles:
            victim = files.pop(0)  # oldest (ids sort chronologically)
            try:
                os.remove(os.path.join(self.spool_dir, victim))
            except OSError:
                pass

    def _files(self) -> List[str]:
        try:
            names = [
                n for n in os.listdir(self.spool_dir)
                if n.startswith("incident_") and n.endswith(".json")
            ]
        except OSError:
            return []
        return sorted(names)

    # -- read ------------------------------------------------------------
    def list(self) -> List[Dict]:
        """Spooled bundles, oldest first: ``{id, trigger, ts, path}``."""
        out = []
        for n in self._files():
            bid = n[len("incident_"):-len(".json")]
            parts = bid.split("_", 2)
            out.append({
                "id": bid,
                "trigger": parts[2] if len(parts) == 3 else "unknown",
                "ts": int(parts[0]) / 1e3 if parts[0].isdigit() else 0.0,
                "path": os.path.join(self.spool_dir, n),
            })
        return out

    def load(self, bundle_id: str) -> Optional[Dict]:
        """One bundle's full JSON (None when unknown). The id is validated
        against the directory listing — it is never joined into a path
        straight from the request."""
        for entry in self.list():
            if entry["id"] == bundle_id:
                try:
                    with open(entry["path"]) as f:
                        return json.load(f)
                except (OSError, ValueError):
                    return None
        return None
