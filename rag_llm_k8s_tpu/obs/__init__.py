"""Observability: metrics registry (Prometheus exposition) + request tracing.

Three pillars (ISSUE 2):

- ``obs.metrics`` — typed Counter/Gauge/Histogram primitives in a
  :class:`~rag_llm_k8s_tpu.obs.metrics.MetricsRegistry`, rendered in
  Prometheus text exposition format (and a flat JSON snapshot for the
  legacy ``/metrics`` consumers);
- ``obs.tracing`` — contextvar-propagated per-request span trees, kept in
  an in-memory ring buffer (``/debug/traces``) and returned inline for
  ``{"trace": true}`` queries; spans wrap device work in
  ``jax.profiler.TraceAnnotation`` so xprof captures show named stages;
- engine instrumentation (TTFT / inter-token / occupancy / compile time)
  lives at the call sites in ``engine/`` and ``server/`` and reports into
  the registry.

The decision layer on top (ISSUE 3):

- ``obs.slo`` — declarative SLOs evaluated over sliding windows of the
  registry's histograms/counters, multi-window burn-rate alerting
  (``GET /slo`` + ``rag_slo_*`` gauges);
- ``obs.logging`` — W3C ``traceparent`` parse/emit and trace-correlated
  structured JSON logs;
- ``obs.devices`` — per-device HBM / prefix-cache residency gauges;
- ``obs.regression`` — the direction-aware bench regression comparator
  behind ``make bench-gate``.

The causal layer (ISSUE 11):

- ``obs.flight`` — the engine flight recorder: a bounded in-process
  journal of typed scheduler/substrate decision events, per-request
  lifecycle timelines (``/debug/timeline/<id>``), and trigger-driven
  incident bundles (``/debug/incidents``; rendered offline by
  ``scripts/flightview.py``).

The efficiency layer (ISSUE 14):

- ``obs.goodput`` — the goodput ledger: per-device-sync-window chip-time
  attribution into a closed category set, an analytic FLOPs/bytes
  roofline (per-executable MFU / bandwidth utilization), per-request
  chip-second + cost figures in ``/generate`` timings, and the
  ``GET /debug/goodput`` capacity report (``flightview --goodput``
  renders the same report offline). Stdlib-only by contract — the
  offline renderer loads it by file path with no jax present.

The quality layer (ISSUE 15):

- ``obs.shadow`` — the shadow-traffic quality auditor: a sampled
  fraction of completed requests re-runs on the EXACT serving path
  (``InferenceEngine.score_exact``) and every divergence from the
  delivered stream is measured and attributed to the approximation that
  served it (warm tier / chunk splice / re-rotation / boundary fixup /
  speculation); ``rag_quality_*`` metrics, the ``quality_p99_logit_err``
  SLO's SLI, ``quality_divergence`` incident bundles, and the
  ``GET /debug/quality`` report (``flightview --quality`` renders the
  same report offline; stdlib-only by the same contract as goodput).
"""

from rag_llm_k8s_tpu.obs.metrics import MetricsRegistry, default_registry  # noqa: F401
from rag_llm_k8s_tpu.obs.tracing import TraceBuffer, span, start_trace  # noqa: F401
