"""Observability: metrics registry (Prometheus exposition) + request tracing.

Three pillars (ISSUE 2):

- ``obs.metrics`` — typed Counter/Gauge/Histogram primitives in a
  :class:`~rag_llm_k8s_tpu.obs.metrics.MetricsRegistry`, rendered in
  Prometheus text exposition format (and a flat JSON snapshot for the
  legacy ``/metrics`` consumers);
- ``obs.tracing`` — contextvar-propagated per-request span trees, kept in
  an in-memory ring buffer (``/debug/traces``) and returned inline for
  ``{"trace": true}`` queries; spans wrap device work in
  ``jax.profiler.TraceAnnotation`` so xprof captures show named stages;
- engine instrumentation (TTFT / inter-token / occupancy / compile time)
  lives at the call sites in ``engine/`` and ``server/`` and reports into
  the registry.
"""

from rag_llm_k8s_tpu.obs.metrics import MetricsRegistry, default_registry  # noqa: F401
from rag_llm_k8s_tpu.obs.tracing import TraceBuffer, span, start_trace  # noqa: F401
