"""W3C trace propagation + trace-correlated structured JSON logs.

Two halves of one correlation story (ISSUE 3):

- **traceparent** (https://www.w3.org/TR/trace-context/): the server parses
  the header on every request and adopts its ``trace-id`` (a malformed or
  absent header falls back to a fresh trace — never an error); the response
  carries ``x-trace-id`` plus a ``traceparent`` naming the server's own span,
  and ``deploy/web/app.py`` originates the header, so one id follows a UI
  click through web → server → span tree → logs.
- **structured logs**: :class:`JsonLogFormatter` renders every log record as
  one JSON object and injects ``trace_id``/``span_id`` from the contextvar
  trace (obs/tracing.py) when the record is emitted inside a traced request
  — grep a trace id across the log stream and you get exactly that
  request's lines. ``configure_json_logging()`` installs it process-wide
  (``TPU_RAG_JSON_LOGS=1`` in server/main.py).

Stdlib-only on purpose: this must import everywhere the package does.
"""

from __future__ import annotations

import json
import logging
import os
import uuid
from typing import NamedTuple, Optional

from rag_llm_k8s_tpu.obs import tracing

__all__ = [
    "TraceContext",
    "parse_traceparent",
    "format_traceparent",
    "new_traceparent",
    "JsonLogFormatter",
    "configure_json_logging",
]

_HEX = set("0123456789abcdef")


class TraceContext(NamedTuple):
    trace_id: str  # 32 lowercase hex
    span_id: str  # 16 lowercase hex (the CALLER's span — our parent)
    sampled: bool


def _is_hex(s: str, width: int) -> bool:
    return len(s) == width and all(c in _HEX for c in s)


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """Strict W3C ``traceparent`` parse; None on ANY malformation.

    ``version-traceid-spanid-flags`` = ``2-32-16-2`` lowercase hex fields.
    Per spec: version ``ff`` is invalid, all-zero trace/span ids are
    invalid, and uppercase hex is invalid. Unknown (valid) versions are
    accepted on the 00 layout — forward compatibility. The caller treats
    None as "no inbound context": a fresh trace, never a 500.
    """
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if not _is_hex(version, 2) or version == "ff":
        return None
    if version == "00" and len(parts) != 4:
        return None
    if not _is_hex(trace_id, 32) or trace_id == "0" * 32:
        return None
    if not _is_hex(span_id, 16) or span_id == "0" * 16:
        return None
    if not _is_hex(flags, 2):
        return None
    return TraceContext(trace_id, span_id, bool(int(flags, 16) & 0x01))


def format_traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def new_traceparent() -> str:
    """Originate a fresh context (the web UI's side of the correlation)."""
    return format_traceparent(uuid.uuid4().hex, uuid.uuid4().hex[:16])


# ---------------------------------------------------------------------------
# structured logs
# ---------------------------------------------------------------------------

# LogRecord attributes that are plumbing, not payload — anything ELSE on the
# record (``extra={...}`` fields) is carried into the JSON object verbatim
_RECORD_INTERNAL = frozenset(
    (
        "name", "msg", "args", "levelname", "levelno", "pathname", "filename",
        "module", "exc_info", "exc_text", "stack_info", "lineno", "funcName",
        "created", "msecs", "relativeCreated", "thread", "threadName",
        "processName", "process", "taskName", "message", "asctime",
    )
)


class JsonLogFormatter(logging.Formatter):
    """One JSON object per log line, trace-correlated via the contextvar.

    A record emitted inside a traced request carries that request's
    ``trace_id`` and the server span id — the SAME ids the response's
    ``x-trace-id`` header and the inline ``{"trace": true}`` tree report
    (pinned by tests/test_slo.py). ``extra={...}`` fields ride along as
    top-level keys (reserved names are dropped rather than collided).
    """

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        tr = tracing.current_trace()
        if tr is not None:
            out["trace_id"] = tr.trace_id
            out["span_id"] = tr.span_id
        for key, val in record.__dict__.items():
            if key in _RECORD_INTERNAL or key.startswith("_") or key in out:
                continue
            try:
                json.dumps(val)
            except (TypeError, ValueError):
                val = repr(val)
            out[key] = val
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, separators=(",", ":"), default=repr)


def configure_json_logging(level: Optional[str] = None) -> None:
    """Swap the root handlers for ONE stderr handler with the JSON
    formatter. Honors ``TPU_RAG_LOG_LEVEL`` (same env server/main.py reads
    for the plain format). Idempotent."""
    root = logging.getLogger()
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler()
    handler.setFormatter(JsonLogFormatter())
    root.addHandler(handler)
    root.setLevel(level or os.environ.get("TPU_RAG_LOG_LEVEL", "INFO"))
