"""Request-scoped tracing: contextvar-propagated span trees per request.

Every ``/generate`` request gets a trace id and a tree of stage spans
(tokenize → retrieve-coalesce wait → fused embed+kNN → prefix resolve →
prefill+decode → detokenize). Spans are recorded in the request thread at
the same boundaries the response's ``timings`` block is measured at, so
the span durations and the timings agree by construction (the acceptance
contract: top-level spans sum to within 5% of ``timings.total_ms``).

Where a stage runs as ONE fused device program (the whole generate loop is
a single executable — by design, see engine/engine.py), the host cannot
observe finer structure wall-clock; those stages appear as one span and
their interior is visible two other ways instead:

- every span body is wrapped in ``jax.profiler.TraceAnnotation``, so an
  xprof capture (``/profile``) shows the named stages on the device
  timeline;
- the per-token view (TTFT / inter-token) comes from the metrics
  histograms the engines feed (``rag_time_to_first_token_seconds``,
  ``rag_decode_inter_token_seconds``) — distribution over all traffic
  rather than one request's timeline.

Finished traces are emitted as structured JSON logs (logger
``rag_llm_k8s_tpu.trace``, DEBUG) and kept in an in-memory ring buffer
served by ``GET /debug/traces``; a client posting ``{"trace": true}`` gets
its own tree inline in the response.
"""

from __future__ import annotations

import contextvars
import json
import logging
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger("rag_llm_k8s_tpu.trace")

try:  # device-timeline names for xprof captures; absent off-JAX is fine
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # noqa: BLE001 — tracing must work without jax
    _TraceAnnotation = None


@dataclass
class Span:
    name: str
    start_s: float  # monotonic
    end_s: Optional[float] = None
    parent: Optional[int] = None  # index into Trace.spans
    attrs: Dict[str, float] = field(default_factory=dict)

    def duration_ms(self) -> float:
        return ((self.end_s if self.end_s is not None else self.start_s)
                - self.start_s) * 1e3


class Trace:
    """One request's span tree. NOT thread-safe on purpose: a trace belongs
    to the request thread that started it (contextvar propagation); stages
    that run on worker threads are accounted for by the request-thread span
    that waits on them (e.g. retrieve-coalesce wait)."""

    def __init__(self, trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None):
        # W3C trace-context width (32 lowercase hex — uuid4().hex exactly):
        # the id round-trips through a ``traceparent`` header unchanged, so
        # a UI-originated trace and the server's span tree correlate. The
        # server-side span id identifies THIS hop (obs/logging.py emits it
        # on every structured log line and in the response traceparent).
        self.trace_id = trace_id or uuid.uuid4().hex
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_span_id = parent_span_id
        self.started_at = time.time()
        self.t0 = time.monotonic()
        self.end_s: Optional[float] = None
        self.spans: List[Span] = []
        self._stack: List[int] = []  # open span indices (nesting)
        self.attrs: Dict[str, object] = {}

    # -- recording -------------------------------------------------------
    def begin(self, name: str) -> int:
        parent = self._stack[-1] if self._stack else None
        self.spans.append(Span(name, time.monotonic(), parent=parent))
        idx = len(self.spans) - 1
        self._stack.append(idx)
        return idx

    def end(self, idx: int) -> None:
        self.spans[idx].end_s = time.monotonic()
        if self._stack and self._stack[-1] == idx:
            self._stack.pop()

    def add_span(self, name: str, start_s: float, duration_s: float,
                 parent: Optional[int] = None, **attrs) -> int:
        """Record an already-measured interval (e.g. the tokenize share a
        coalesced worker measured and returned as a number) as a span."""
        if parent is None and self._stack:
            parent = self._stack[-1]
        sp = Span(name, start_s, start_s + duration_s, parent=parent)
        sp.attrs.update({k: float(v) for k, v in attrs.items()})
        self.spans.append(sp)
        return len(self.spans) - 1

    # -- export ----------------------------------------------------------
    def total_ms(self) -> float:
        end = self.end_s if self.end_s is not None else time.monotonic()
        return (end - self.t0) * 1e3

    def to_dict(self) -> Dict:
        children: Dict[Optional[int], List[int]] = {}
        for i, sp in enumerate(self.spans):
            children.setdefault(sp.parent, []).append(i)

        def node(i: int) -> Dict:
            sp = self.spans[i]
            d = {
                "name": sp.name,
                "start_ms": round((sp.start_s - self.t0) * 1e3, 3),
                "duration_ms": round(sp.duration_ms(), 3),
            }
            if sp.attrs:
                d["attrs"] = {k: round(v, 3) for k, v in sp.attrs.items()}
            kids = [node(j) for j in children.get(i, [])]
            if kids:
                d["spans"] = kids
            return d

        out = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "started_at": self.started_at,
            "total_ms": round(self.total_ms(), 3),
            "spans": [node(i) for i in children.get(None, [])],
        }
        if self.parent_span_id:
            out["parent_span_id"] = self.parent_span_id
        if self.attrs:
            out["attrs"] = self.attrs
        return out


_current: "contextvars.ContextVar[Optional[Trace]]" = contextvars.ContextVar(
    "rag_trace", default=None
)


def current_trace() -> Optional[Trace]:
    return _current.get()


def start_trace(trace_id: Optional[str] = None,
                parent_span_id: Optional[str] = None) -> Trace:
    """Open a trace on this thread; pair with ``finish_trace``.
    ``trace_id``/``parent_span_id`` come from an incoming W3C
    ``traceparent`` header when the request carried one
    (obs/logging.py:parse_traceparent)."""
    tr = Trace(trace_id, parent_span_id=parent_span_id)
    _current.set(tr)
    return tr


def finish_trace(tr: Trace, buffer: "Optional[TraceBuffer]" = None) -> Dict:
    """Close the trace: close dangling spans, emit the structured JSON log,
    push into the ring buffer, clear the contextvar. Returns the tree."""
    now = time.monotonic()
    tr.end_s = now
    for idx in reversed(tr._stack):  # an exception can leave spans open
        if tr.spans[idx].end_s is None:
            tr.spans[idx].end_s = now
    tr._stack.clear()
    if _current.get() is tr:
        _current.set(None)
    tree = tr.to_dict()
    if logger.isEnabledFor(logging.DEBUG):
        logger.debug("%s", json.dumps(tree, separators=(",", ":")))
    if buffer is not None:
        buffer.add(tree)
    return tree


@contextmanager
def span(name: str, **attrs):
    """Record a stage span on the current trace (no-op cost when no trace
    is active beyond the TraceAnnotation), and name the wrapped device work
    on the xprof timeline either way."""
    tr = _current.get()
    idx = None
    if tr is not None:
        idx = tr.begin(name)
        if attrs:
            tr.spans[idx].attrs.update({k: float(v) for k, v in attrs.items()})
    ann = _TraceAnnotation(name) if _TraceAnnotation is not None else None
    if ann is not None:
        ann.__enter__()
    try:
        yield tr.spans[idx] if idx is not None else None
    finally:
        if ann is not None:
            ann.__exit__(None, None, None)
        if tr is not None and idx is not None:
            tr.end(idx)


class TraceBuffer:
    """Fixed-capacity ring of finished trace trees (``/debug/traces``)."""

    def __init__(self, capacity: int = 128):
        self._lock = threading.Lock()
        self._buf: "deque[Dict]" = deque(maxlen=capacity)

    def add(self, tree: Dict) -> None:
        with self._lock:
            self._buf.append(tree)

    def list(self, limit: Optional[int] = None) -> List[Dict]:
        """Newest-last. ``limit`` trims to the newest N; non-positive
        limits mean "no trim" (a negative slice would silently DROP the
        oldest entry instead)."""
        with self._lock:
            items = list(self._buf)
        return items[-limit:] if limit is not None and limit > 0 else items

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)
