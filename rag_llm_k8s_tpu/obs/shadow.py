"""Shadow-traffic quality auditor: online divergence tracking for every
approximation in the serving path.

The serving stack runs four lossy-by-contract approximations in
production — int8 warm-tier KV, chunk-granular splice with boundary
correction, speculative verify windows, and prefix reuse generally — but
their quality contracts (warm logit tolerance 0.15, splice logit_max_err
<= 0.15, spec byte-identity) were pinned only in tests and bench legs,
never observed on live traffic. This module is that observation:

- :class:`ShadowAuditor` re-runs a sampled fraction of completed live
  requests on the EXACT path — no prefix reuse, no speculation, the
  engine's native KV dtype — via an injected ``score_fn`` (in production,
  ``InferenceEngine.score_exact``: one teacher-forced chunked forward over
  prompt + delivered tokens on the ONE-SHOT engine, so the continuous
  pool's blocks are untouched). Audits ride a single bounded worker and a
  headroom gate (the lookahead executor's discipline: breaker open or a
  queued admission line defers the audit — shadow work never competes
  with live traffic).
- **comparison**: the delivered stream is judged token by token against
  the exact path's argmax chain. ``first_div`` is the first position the
  streams disagree; ``logit_err`` is HALF the exact-path logit gap between
  the exact argmax and the delivered token at that position — the smallest
  symmetric logit perturbation that explains the delivered choice, so an
  approximation whose pinned per-logit tolerance is 0.15 can never produce
  a divergence measuring above 0.15. Greedy byte-identity contracts
  (exact-chain reuse, paged speculation) audit at divergence rate 0.0 by
  construction. Sampled (non-greedy) requests cannot be judged this way
  and are counted ``skipped{reason="sampled"}``.
- **attribution**: every audit carries the request's approximation
  fingerprint (:data:`APPROXIMATIONS` — derived engine-side: the prefix
  cache stamps ``CachedPrefix.approx`` per resolve, speculation stamps the
  per-request ledger), so a divergence names the approximation that was
  active when it happened.
- **one report, two sources**: the per-audit facts are journaled as
  ``shadow_audit`` flight events, and ``render_report`` over
  ``state_from_events`` rebuilds EXACTLY the report the live auditor's
  ``state()`` renders — ``GET /debug/quality`` and
  ``scripts/flightview.py --quality`` cannot drift apart (the goodput
  ledger's same-report contract, applied to quality).

STDLIB-ONLY BY CONTRACT: flightview loads this module by file path with
no jax (or numpy) importable — the score_fn return values are consumed as
plain sequences, and journaling goes through an injected ``emit`` hook
(the service's, which calls ``flight.emit`` with literal event names so
ragcheck's EVENT-REGISTRY sees the sites).

Configuration comes through ``core/config.py::ShadowConfig`` (env
``TPU_RAG_SHADOW*``) — this module reads no environment itself.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "APPROXIMATIONS",
    "ERR_BUCKETS",
    "POS_BUCKETS",
    "SCHEMA_VERSION",
    "SKIP_REASONS",
    "ShadowAuditor",
    "new_state",
    "record",
    "render_report",
    "state_from_events",
]

logger = logging.getLogger(__name__)

#: report schema; flightview --quality refuses newer versions it does not
#: know (the flight-bundle discipline)
SCHEMA_VERSION = 1

#: the CLOSED approximation catalog a fingerprint may name (plus the
#: implicit "none" for requests that served with every approximation off)
APPROXIMATIONS = (
    "prefix_reuse",    # cached-KV reuse engaged (lossless by contract)
    "warm_tier",       # int8 warm-tier KV served (bounded drift)
    "splice",          # chunk-granular splice at a non-canonical placement
    "rerotate",        # RoPE delta re-rotation of cached K planes
    "boundary_fixup",  # bounded boundary-correction re-prefill
    "spec_verify",     # speculative draft-and-verify (byte-identical)
)

#: why a SELECTED audit did not run (unsampled requests are not skips)
SKIP_REASONS = (
    "sampled",   # non-greedy request: no deterministic exact reference
    "empty",     # nothing was emitted, nothing to compare
    "no_prompt", # the serving path could not reconstruct the prompt ids
    "oversize",  # prompt + stream exceeds the exact path's scoring cap
    "backlog",   # the bounded audit queue was full
    "headroom",  # live traffic never left the device idle long enough
)

#: logit-error histogram ladder (upper bounds; +Inf overflow implied).
#: 0.15 is a bucket bound ON PURPOSE: it is the pinned warm/splice
#: tolerance, and the quality SLO evaluates at exactly that bound.
ERR_BUCKETS = (0.01, 0.02, 0.05, 0.1, 0.15, 0.25, 0.5, 1.0, 2.5)

#: first-divergence-token histogram ladder (upper bounds, 0-indexed
#: emitted position; +Inf overflow implied)
POS_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

_OUTCOMES = ("clean", "diverged", "skipped", "failed")


def _bucket_index(value: float, bounds: Sequence[float]) -> int:
    """Index of the first bound >= value, len(bounds) for overflow —
    the same "observation <= bound lands in the bucket" rule the metrics
    registry's histograms use, so the SLO's bucket math and this module's
    agree on what 0.15 means."""
    for i, b in enumerate(bounds):
        if value <= b:
            return i
    return len(bounds)


def _hist_labels(bounds: Sequence[float]) -> List[str]:
    return [f"le_{b:g}" for b in bounds] + ["inf"]


def new_state() -> Dict:
    """An empty accumulator — everything in it is derivable from the
    ``shadow_audit`` journal events alone (the same-report contract)."""
    return {
        "schema_version": SCHEMA_VERSION,
        "audits": {k: 0 for k in _OUTCOMES},
        "skips": {},
        "attribution": {},  # approximation -> {"clean": n, "diverged": n}
        "tokens_compared": 0,
        "err_hist": [0] * (len(ERR_BUCKETS) + 1),
        "pos_hist": [0] * (len(POS_BUCKETS) + 1),
        "err_max": 0.0,
        "tenants": {},  # tenant -> {"clean": n, "diverged": n}
    }


def record(state: Dict, ev: Dict) -> None:
    """Fold one audit event's facts into ``state`` — used verbatim by the
    live auditor and by ``state_from_events``, so the two can only agree."""
    oc = ev.get("outcome")
    if oc not in state["audits"]:
        return
    state["audits"][oc] += 1
    if oc == "skipped":
        reason = str(ev.get("reason", "unknown"))
        state["skips"][reason] = state["skips"].get(reason, 0) + 1
        return
    if oc == "failed":
        return
    state["tokens_compared"] += int(ev.get("n", 0))
    approx = list(ev.get("approx") or []) or ["none"]
    for a in approx:
        slot = state["attribution"].setdefault(a, {"clean": 0, "diverged": 0})
        slot[oc] += 1
    tenant = ev.get("tenant")
    if tenant:
        trow = state.setdefault("tenants", {}).setdefault(
            str(tenant), {"clean": 0, "diverged": 0}
        )
        trow[oc] += 1
    err = float(ev.get("err", 0.0))
    state["err_hist"][_bucket_index(err, ERR_BUCKETS)] += 1
    if err > state["err_max"]:
        state["err_max"] = err
    if oc == "diverged":
        pos = int(ev.get("pos", 0))
        state["pos_hist"][_bucket_index(pos, POS_BUCKETS)] += 1


def state_from_events(events: Sequence[Dict]) -> Dict:
    """Rebuild the auditor state from a journal/bundle's ``shadow_audit``
    events — the offline half of the same-report contract."""
    st = new_state()
    for e in sorted(events, key=lambda e: e.get("seq", 0)):
        if e.get("type") == "shadow_audit":
            record(st, e)
    return st


def _quantile(hist: Sequence[int], bounds: Sequence[float], q: float,
              overflow: float) -> float:
    """The smallest bucket bound covering fraction ``q`` of observations
    (``overflow`` — in practice the tracked max — when the quantile lands
    past the ladder). 0.0 on an empty histogram."""
    total = sum(hist)
    if total == 0:
        return 0.0
    need = q * total
    cum = 0
    for i, b in enumerate(bounds):
        cum += hist[i]
        if cum >= need:
            return float(b)
    return float(overflow)


def render_report(state: Dict) -> Dict:
    """The quality report — served live by ``GET /debug/quality`` and
    rebuilt offline by ``flightview --quality`` from the same function."""
    audits = dict(state["audits"])
    judged = audits["clean"] + audits["diverged"]
    rate = (audits["diverged"] / judged) if judged else 0.0
    err_hist = {
        lbl: int(n)
        for lbl, n in zip(_hist_labels(ERR_BUCKETS), state["err_hist"])
    }
    pos_hist = {
        lbl: int(n)
        for lbl, n in zip(_hist_labels(POS_BUCKETS), state["pos_hist"])
    }
    return {
        "schema_version": state.get("schema_version", SCHEMA_VERSION),
        "audits": audits,
        "divergence_rate": round(rate, 6),
        "skips": dict(state["skips"]),
        "attribution": {
            a: dict(v) for a, v in sorted(state["attribution"].items())
        },
        "tokens_compared": int(state["tokens_compared"]),
        "logit_err": {
            "p50": _quantile(
                state["err_hist"], ERR_BUCKETS, 0.5, state["err_max"]
            ),
            "p99": _quantile(
                state["err_hist"], ERR_BUCKETS, 0.99, state["err_max"]
            ),
            "max": round(float(state["err_max"]), 6),
            "hist": err_hist,
        },
        "first_divergence_token": {
            "p50": _quantile(state["pos_hist"], POS_BUCKETS, 0.5,
                             POS_BUCKETS[-1]),
            "hist": pos_hist,
        },
        # per-tenant judged-audit split (ISSUE 18): which tenant's traffic
        # the divergences landed on — absent tenants simply never appear,
        # so old journals render an empty dict, not an error
        "tenants": {
            t: dict(v)
            for t, v in sorted(state.get("tenants", {}).items())
        },
    }


class _Job:
    __slots__ = ("request_id", "prompt", "emitted", "approx", "tenant")

    def __init__(self, request_id, prompt, emitted, approx, tenant=None):
        self.request_id = request_id
        self.prompt = prompt
        self.emitted = emitted
        self.approx = approx
        self.tenant = tenant


class ShadowAuditor:
    """Sampled shadow-execution auditor over completed live requests.

    ``observe()`` is called once per delivered response (serving thread:
    one rng draw and, when selected, one bounded enqueue — never device
    work). One daemon worker drains the queue, waits out the headroom
    gate, runs ``score_fn(prompt_ids, emitted_ids)`` and folds the
    comparison into the state; per-audit facts go to ``on_result`` (the
    service journals them as ``shadow_audit`` flight events and feeds the
    metric histograms) and a second diverged audit inside
    ``burst_window_s`` fires ``on_burst`` (the service spools a
    ``quality_divergence`` incident bundle).

    ``rng``/``clock`` are injectable so tests drive sampling and the
    burst window deterministically.
    """

    #: headroom polls before a queued audit is abandoned as "headroom"
    _HEADROOM_TRIES = 40
    _HEADROOM_SLEEP_S = 0.05

    def __init__(
        self,
        config,
        score_fn: Callable[[Sequence[int], Sequence[int]], Dict],
        headroom_fn: Optional[Callable[[], bool]] = None,
        on_result: Optional[Callable[[Optional[int], Dict], None]] = None,
        on_burst: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        rng=None,
    ):
        config.validate()
        self.config = config
        self.score_fn = score_fn
        self.headroom_fn = headroom_fn
        self.on_result = on_result
        self.on_burst = on_burst
        self.clock = clock
        if rng is None:
            import random

            rng = random.Random()
        self._rng = rng
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._state = new_state()
        self._seen = 0
        self._selected = 0
        self._div_stamps: deque = deque()
        self._stop = False
        self._inflight = False  # a popped job the worker is still judging
        self._worker: Optional[threading.Thread] = None

    # -- serving-thread side ---------------------------------------------
    def observe(
        self,
        emitted: Sequence[int],
        approx: Tuple[str, ...] = (),
        request_id: Optional[int] = None,
        prompt_ids: Optional[Sequence[int]] = None,
        prompt_fn: Optional[Callable[[], Optional[Sequence[int]]]] = None,
        eligible: bool = True,
        ineligible_reason: str = "sampled",
        force: bool = False,
        tenant: Optional[str] = None,
    ) -> bool:
        """One delivered response. Returns True when an audit was enqueued.

        ``eligible=False`` marks a request the exact path cannot judge (a
        non-greedy stream); the reason is counted only when the sampler
        actually selected it — unsampled traffic is not a "skip".
        ``prompt_fn`` defers prompt-id reconstruction to selection time so
        the 95% unsampled case never pays it. ``force`` bypasses the
        sampler (the smoke lane and tests). ``tenant`` (edge-interned)
        rides the audit so divergence attributes to the tenant whose
        traffic exercised the approximation."""
        with self._lock:
            self._seen += 1
        if not self.config.enabled:
            return False
        if not force and not (self._rng.random() < self.config.sample_rate):
            return False
        with self._lock:
            self._selected += 1
        if not eligible:
            self._skip(request_id, ineligible_reason, tenant=tenant)
            return False
        if not emitted:
            self._skip(request_id, "empty", tenant=tenant)
            return False
        if prompt_ids is None and prompt_fn is not None:
            try:
                prompt_ids = prompt_fn()
            except Exception:  # noqa: BLE001 — audit prep must not fail serving
                logger.exception("shadow prompt reconstruction failed")
                prompt_ids = None
        if not prompt_ids:
            self._skip(request_id, "no_prompt", tenant=tenant)
            return False
        job = _Job(
            request_id, [int(t) for t in prompt_ids],
            [int(t) for t in emitted], tuple(approx), tenant=tenant,
        )
        with self._lock:
            if self._stop:
                return False
            if len(self._queue) >= self.config.backlog:
                pass  # counted outside the lock below
            else:
                self._queue.append(job)
                self._ensure_worker_locked()
                self._cv.notify()
                return True
        self._skip(request_id, "backlog", tenant=tenant)
        return False

    # -- worker side ------------------------------------------------------
    def _ensure_worker_locked(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, name="shadow-audit", daemon=True
            )
            self._worker.start()

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._stop:
                    self._cv.wait(0.5)
                if self._stop and not self._queue:
                    return
                job = self._queue.popleft()
                self._inflight = True
            try:
                if not self._await_headroom():
                    self._skip(job.request_id, "headroom", tenant=job.tenant)
                    continue
                try:
                    ev = self._audit(job)
                except ValueError:
                    # the scorer declined the shape (prompt + stream over
                    # its cap) — an honest skip, not a failure
                    self._skip(job.request_id, "oversize", tenant=job.tenant)
                    continue
                except Exception:  # noqa: BLE001 — an audit crash must stay contained
                    logger.exception(
                        "shadow audit failed (request %s)", job.request_id
                    )
                    ev = {
                        "outcome": "failed", "n": 0,
                        "approx": list(job.approx),
                    }
                    if job.tenant:
                        ev["tenant"] = job.tenant
                self._finish(job.request_id, ev)
            finally:
                with self._lock:
                    self._inflight = False

    def _await_headroom(self) -> bool:
        """Wait for live traffic to leave the device alone; give up after
        the bounded poll budget (the audit is then an honest skip — shadow
        work must never queue behind a saturated serving path)."""
        if self.headroom_fn is None:
            return True
        for _ in range(self._HEADROOM_TRIES):
            with self._lock:
                if self._stop:
                    return False
            try:
                if self.headroom_fn():
                    return True
            except Exception:  # noqa: BLE001 — a broken gate must not kill the worker
                logger.exception("shadow headroom probe failed")
                return False
            time.sleep(self._HEADROOM_SLEEP_S)
        return False

    def _audit(self, job: _Job) -> Dict:
        """Run the exact-path replay and compare: first token where the
        exact argmax chain disagrees with the delivered stream, and the
        minimal logit perturbation that explains the delivered token."""
        score = self.score_fn(job.prompt, job.emitted)
        argmax = score["argmax"]
        tn = {"tenant": job.tenant} if job.tenant else {}
        first_div = None
        for t, tok in enumerate(job.emitted):
            if int(argmax[t]) != int(tok):
                first_div = t
                break
        if first_div is None:
            return {
                "outcome": "clean", "n": len(job.emitted), "err": 0.0,
                "approx": list(job.approx), **tn,
            }
        gap = float(score["max_logit"][first_div]) - float(
            score["chosen_logit"][first_div]
        )
        return {
            "outcome": "diverged",
            "n": first_div + 1,  # tokens compared up to the divergence
            "pos": first_div,
            "err": round(max(gap, 0.0) / 2.0, 6),
            "approx": list(job.approx), **tn,
        }

    def _skip(self, request_id: Optional[int], reason: str,
              tenant: Optional[str] = None) -> None:
        ev = {"outcome": "skipped", "reason": reason, "n": 0}
        if tenant:
            ev["tenant"] = tenant
        self._finish(request_id, ev)

    def _finish(self, request_id: Optional[int], ev: Dict) -> None:
        with self._lock:
            record(self._state, ev)
            burst = False
            if ev.get("outcome") == "diverged":
                now = self.clock()
                self._div_stamps.append(now)
                cutoff = now - float(self.config.burst_window_s)
                while self._div_stamps and self._div_stamps[0] < cutoff:
                    self._div_stamps.popleft()
                burst = len(self._div_stamps) >= 2
        hook = self.on_result
        if hook is not None:
            try:
                hook(request_id, dict(ev))
            except Exception:  # noqa: BLE001 — observers must not kill the worker
                logger.exception("shadow on_result hook failed")
        if burst and self.on_burst is not None:
            try:
                self.on_burst()
            except Exception:  # noqa: BLE001
                logger.exception("shadow on_burst hook failed")

    # -- readers ----------------------------------------------------------
    def state(self) -> Dict:
        """A consistent copy of the journal-derivable accumulator."""
        with self._lock:
            st = self._state
            return {
                "schema_version": st["schema_version"],
                "audits": dict(st["audits"]),
                "skips": dict(st["skips"]),
                "attribution": {
                    a: dict(v) for a, v in st["attribution"].items()
                },
                "tokens_compared": st["tokens_compared"],
                "err_hist": list(st["err_hist"]),
                "pos_hist": list(st["pos_hist"]),
                "err_max": st["err_max"],
                "tenants": {
                    t: dict(v) for t, v in st.get("tenants", {}).items()
                },
            }

    def stats(self) -> Dict[str, float]:
        """Flat numbers for the metric callbacks (seen/selected are
        auditor-local sampling facts, deliberately NOT in the report —
        the report holds only what the journal can reproduce)."""
        with self._lock:
            st = self._state
            judged = st["audits"]["clean"] + st["audits"]["diverged"]
            out: Dict[str, float] = {
                "seen": float(self._seen),
                "selected": float(self._selected),
                "backlog_depth": float(len(self._queue)),
                "divergence_rate": (
                    st["audits"]["diverged"] / judged if judged else 0.0
                ),
            }
            for oc, n in st["audits"].items():
                out[f"audits_{oc}"] = float(n)
            for r in SKIP_REASONS:
                out[f"skip_{r}"] = float(st["skips"].get(r, 0))
            for a, v in st["attribution"].items():
                out[f"attr_{a}_clean"] = float(v["clean"])
                out[f"attr_{a}_diverged"] = float(v["diverged"])
            return out

    def drain(self, timeout: float = 5.0) -> bool:
        """Block until the queue is empty and the worker idles (tests and
        the smoke lane; serving never calls this)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and not self._inflight:
                    return True
            time.sleep(0.01)
        return False

    def shutdown(self) -> None:
        with self._lock:
            self._stop = True
            self._cv.notify_all()
            worker = self._worker
        if worker is not None:
            worker.join(timeout=5.0)
