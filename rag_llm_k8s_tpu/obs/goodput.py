"""Goodput ledger: per-window chip-time attribution + roofline accounting.

The obs stack up to PR 11 says *what happened* (metrics/traces), *when to
care* (SLO burn), and *why* (the flight journal) — but nothing measures
how efficiently the chips were USED. ROADMAP item 3 (disaggregated
prefill/decode pools + affinity router) is gated on NinjaLLM's cost
framing — tokens/s/$ under concurrency, not per-chip peak — and splitting
prefill (MFU-bound) from decode (bandwidth-bound) into separately-scaled
pools first needs telemetry that proves where chip-seconds actually go.

This module is that substrate:

- :data:`CATEGORIES` — the CLOSED attribution set every device sync
  window decomposes into. Per window the six non-idle categories sum to
  exactly the window's measured duration (the conservation invariant
  tests/test_goodput.py pins); ``idle`` is derived (wall − busy).
- :class:`RooflineModel` — an analytic FLOPs/bytes model derived from the
  model config (params, heads, block layout, dtypes): classifies each
  executable kind as compute- vs bandwidth-bound (arithmetic intensity vs
  the chip's ridge point) and yields per-window MFU / bandwidth-
  utilization estimates. MFU here credits only REAL token lanes —
  padding lanes execute but earn nothing, so ``mfu × peak`` reads as
  useful-work throughput, the router's capacity signal.
- :class:`GoodputLedger` — the engine-side step ledger. The engines call
  ``record_*`` once per device sync window (scheduler/dispatcher thread
  only); each call updates the rolling per-category chip-second totals,
  the per-kind roofline aggregates, and the per-request attribution map,
  and returns the window summary the caller journals as a
  ``goodput_window`` flight event — so ``scripts/flightview.py
  --goodput`` reconstructs the SAME report offline from a journal or
  incident bundle that ``GET /debug/goodput`` renders live.

Attribution model (docs/GOODPUT.md has the worked arithmetic):

- a window of duration ``d`` with ``A`` active requests attributes
  ``d / A`` chip-seconds to each (the device computes every row in
  lockstep — concurrency is what the batch shape gives you), so
  concurrent requests' attributed chip-seconds sum to the scheduler's
  measured busy time by construction;
- within the window, ``d`` splits across categories by weighted lane
  counts: useful decode lanes, drafted-but-rejected verify lanes,
  computed prefill tokens, re-fed tokens after a preemption/reset
  (``preempt_rework``), splice/scatter service of reused KV
  (``prefill_skipped``, weighted by the roofline's copy-vs-compute
  ratio), and everything else — inactive rows, right-pad slack,
  post-EOS lanes — as ``padding_bubble``.

Import discipline: stdlib-only, and no package-internal imports — the
offline renderer (``scripts/flightview.py``) loads this file directly by
path so a laptop holding nothing but a bundle needs no jax. The flight
event is therefore emitted by the CALLER (the engines already import
``obs.flight``), from the summary dict ``record_*`` returns.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "CATEGORIES",
    "WINDOW_CATEGORIES",
    "KINDS",
    "GoodputLedger",
    "RooflineModel",
    "ledger_for",
    "merge_states",
    "render_report",
    "roofline_for_llama",
    "state_from_events",
]

#: The closed attribution set. The first six decompose every device sync
#: window (they sum to the window's duration); ``idle`` is wall − busy,
#: derived at report time — a window is never idle by definition.
CATEGORIES = (
    "prefill_compute",
    "prefill_skipped",
    "decode_useful",
    "spec_rejected",
    "padding_bubble",
    "preempt_rework",
    "idle",
)
WINDOW_CATEGORIES = CATEGORIES[:-1]

#: Executable kinds the ledger aggregates roofline figures per.
KINDS = ("prefill", "prefill_px", "decode", "verify", "oneshot", "mixed")

#: Generic single-chip peaks used when the config does not pin them
#: (TPU_RAG_GOODPUT_PEAK_TFLOPS / TPU_RAG_GOODPUT_HBM_GBS): a TPU-v4-class
#: 275 bf16 TFLOP/s and 1.2 TB/s HBM. On CPU hosts the absolute MFU is
#: meaningless-small but every RELATIVE read (category split, bubble
#: fraction, per-request attribution, regression direction) still holds.
DEFAULT_PEAK_TFLOPS = 275.0
DEFAULT_HBM_GBS = 1200.0


class RooflineModel:
    """Analytic per-token FLOPs/bytes figures for one model config.

    All inputs are plain numbers (no jax) so the offline renderer can
    instantiate one from a bundle's config fingerprint if it ever needs
    to — though the ``goodput_window`` events carry their per-window
    mfu/bw/bound precomputed exactly so it normally does not.
    """

    def __init__(
        self,
        flops_per_token: float,
        weight_bytes: float,
        kv_bytes_per_token: float,
        peak_tflops: float = 0.0,
        hbm_gbs: float = 0.0,
    ):
        if flops_per_token <= 0 or weight_bytes <= 0 or kv_bytes_per_token <= 0:
            raise ValueError("roofline figures must be positive")
        self.flops_per_token = float(flops_per_token)
        self.weight_bytes = float(weight_bytes)
        self.kv_bytes_per_token = float(kv_bytes_per_token)
        self.peak_flops = (
            float(peak_tflops) if peak_tflops > 0 else DEFAULT_PEAK_TFLOPS
        ) * 1e12
        self.peak_bytes = (
            float(hbm_gbs) if hbm_gbs > 0 else DEFAULT_HBM_GBS
        ) * 1e9

    # -- derived ---------------------------------------------------------
    @property
    def ridge(self) -> float:
        """FLOPs/byte above which the chip is compute-bound."""
        return self.peak_flops / self.peak_bytes

    @property
    def t_compute_token(self) -> float:
        """Best-case seconds to COMPUTE one token's forward."""
        return self.flops_per_token / self.peak_flops

    @property
    def t_copy_token(self) -> float:
        """Best-case seconds to MOVE one token's KV (read + write)."""
        return 2.0 * self.kv_bytes_per_token / self.peak_bytes

    @property
    def splice_weight(self) -> float:
        """Relative per-token cost of SERVING a reused-KV token (a
        bandwidth-bound splice/scatter/re-rotation) vs computing one — the
        lane weight ``prefill_skipped`` earns in a window's split. Clamped
        so a degenerate config can neither zero out reuse service time nor
        claim a copy costs more than the compute it saved."""
        w = self.t_copy_token / max(self.t_compute_token, 1e-30)
        return min(max(w, 1e-4), 1.0)

    def classify(self, flops: float, nbytes: float) -> str:
        """'compute' | 'bandwidth' by arithmetic intensity vs the ridge."""
        intensity = flops / max(nbytes, 1e-30)
        return "compute" if intensity >= self.ridge else "bandwidth"

    def mfu(self, flops: float, seconds: float) -> float:
        return flops / max(seconds * self.peak_flops, 1e-30)

    def bw_util(self, nbytes: float, seconds: float) -> float:
        return nbytes / max(seconds * self.peak_bytes, 1e-30)


def roofline_for_llama(
    num_layers: int,
    hidden_size: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    intermediate_size: int,
    vocab_size: int,
    weight_bytes_per_param: float = 2.0,
    kv_quant: str = "bf16",
    peak_tflops: float = 0.0,
    hbm_gbs: float = 0.0,
) -> RooflineModel:
    """The serving stack's roofline from a LlamaConfig's fields.

    ``flops_per_token ≈ 2 × matmul params`` (attention-score FLOPs are
    context-dependent and second-order at serving context lengths —
    docs/GOODPUT.md shows the bound); ``weight_bytes`` is the full
    streamed parameter footprint a decode step reads once per batch;
    ``kv_bytes_per_token`` is one position's K+V across all layers (plus
    fp32 scale planes under int8 KV).
    """
    L, d = int(num_layers), int(hidden_size)
    H, K, hd = int(num_heads), int(num_kv_heads), int(head_dim)
    inter, V = int(intermediate_size), int(vocab_size)
    matmul_params = L * (
        d * H * hd          # q projection
        + 2 * d * K * hd    # k, v projections
        + H * hd * d        # o projection
        + 3 * d * inter     # gate / up / down
    ) + V * d               # lm head
    kv_b = 1 if kv_quant == "int8" else 2
    kv_bytes = 2 * L * K * hd * kv_b
    if kv_quant == "int8":
        kv_bytes += 2 * L * K * 4  # per-position fp32 scale planes
    # weight_bytes = the matmul params a decode step actually STREAMS
    # (lm head included via matmul_params); the embedding table is a
    # per-token row gather, not a full stream — counting it would
    # overstate decode bytes ~7% at 8B scale
    return RooflineModel(
        flops_per_token=2.0 * matmul_params,
        weight_bytes=matmul_params * float(weight_bytes_per_param),
        kv_bytes_per_token=float(kv_bytes),
        peak_tflops=peak_tflops,
        hbm_gbs=hbm_gbs,
    )


def ledger_for(model_config, engine_config) -> "GoodputLedger":
    """THE ledger constructor both serving engines share (duck-typed over
    the config dataclasses — still no package imports). One site means the
    two engines' rooflines cannot drift: ``merge_states`` sums their
    states into one report, which is only meaningful when both were
    derived from the same arithmetic."""
    gp = getattr(engine_config, "goodput", None)
    return GoodputLedger(
        roofline_for_llama(
            model_config.num_layers, model_config.hidden_size,
            model_config.num_heads, model_config.num_kv_heads,
            model_config.head_dim, model_config.intermediate_size,
            model_config.vocab_size,
            weight_bytes_per_param=(
                1.0 if getattr(engine_config, "weight_quant", "bf16") == "int8"
                else 2.0
            ),
            kv_quant=getattr(engine_config, "kv_quant", "bf16"),
            peak_tflops=getattr(gp, "peak_tflops", 0.0) or 0.0,
            hbm_gbs=getattr(gp, "hbm_gbs", 0.0) or 0.0,
        ),
        enabled=getattr(gp, "enabled", True),
        chip_hour_usd=getattr(gp, "chip_hour_usd", 0.0) or 0.0,
    )


def _percentile(values: Sequence[float], q: float) -> Optional[float]:
    if not values:
        return None
    s = sorted(values)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


class GoodputLedger:
    """The engine-side step ledger (one per engine; ON by default).

    Writers (``record_*`` / ``pop_request``) run on the engine's owning
    thread only; readers (``state`` / ``totals``, the /metrics callbacks
    and ``/debug/goodput``) come from scrape threads — a single tiny lock
    over plain dict math covers both, and no record ever touches device
    state (the ``goodput_overhead`` bench leg holds the whole ledger to
    ≤ 2% of B=8 decode steps/s).
    """

    MAX_REQUESTS = 8192  # raw-engine callers (tests, benches) never pop
    COST_RING = 512      # completed-request chip_s ring (percentiles)
    # distinct per-tenant rollup rows (interned names churn slowly through
    # the top-K tracker; when even that overflows, the coldest row folds
    # into __other__ — the rollup can never grow with raw-tenant traffic)
    MAX_TENANT_ROWS = 64
    OTHER_TENANT = "__other__"

    def __init__(
        self,
        roofline: RooflineModel,
        enabled: bool = True,
        chip_hour_usd: float = 0.0,
    ):
        self.roofline = roofline
        self.enabled = bool(enabled)
        self.chip_hour_usd = max(0.0, float(chip_hour_usd))
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._cat_s: Dict[str, float] = {c: 0.0 for c in WINDOW_CATEGORIES}
        self._kinds: Dict[str, Dict[str, float]] = {}
        self._busy_s = 0.0
        self._attributed_s = 0.0
        self._useful_decode_tokens = 0.0
        self._requests: Dict[int, Dict[str, float]] = {}
        self._completed: "deque[float]" = deque(maxlen=self.COST_RING)
        # tenant attribution: rid -> interned tenant (stamped at submit),
        # folded into the per-tenant rollup when the request pops
        self._rid_tenant: Dict[int, str] = {}
        self._tenant_roll: Dict[str, Dict[str, float]] = {}

    # ------------------------------------------------------------------
    # recording (engine thread)
    # ------------------------------------------------------------------
    def _req(self, rid: int) -> Dict[str, float]:
        r = self._requests.get(rid)
        if r is None:
            if len(self._requests) >= self.MAX_REQUESTS:
                # drop the OLDEST half (dict preserves insertion order):
                # raw-engine callers never pop, so stale entries accrete —
                # but a wholesale clear would also wipe every in-flight
                # request's accrued chip time and under-bill its delivery
                for k in list(self._requests)[: self.MAX_REQUESTS // 2]:
                    del self._requests[k]
            r = self._requests[rid] = {
                "chip_s": 0.0, "useful_s": 0.0,
                "spec_drafted": 0.0, "spec_accepted": 0.0,
                "spec_windows": 0.0,
            }
        return r

    def discard_request(self, rid: int) -> None:
        """Drop a request that will never be delivered (gave up, deadline
        eviction, scheduler shutdown) — its attribution stays in the
        aggregate totals (the chip time WAS spent) but must not linger in
        the per-request map nor enter the completed-cost percentiles."""
        with self._lock:
            self._requests.pop(rid, None)
            self._rid_tenant.pop(rid, None)

    # ------------------------------------------------------------------
    # tenant attribution (engine/scheduler thread)
    # ------------------------------------------------------------------
    def note_tenant(self, rid: int, tenant: str) -> None:
        """Stamp the (edge-interned, cardinality-bounded) tenant a request
        belongs to; the request's chip time folds into that tenant's
        rollup when it pops. NOT gated on ``enabled``: the map also serves
        ``tenant_of`` (the engine stamps ``admit`` events from it), which
        must work with chip-time attribution off. Cheap — one dict write."""
        if tenant is None:
            return
        with self._lock:
            if len(self._rid_tenant) >= self.MAX_REQUESTS:
                for k in list(self._rid_tenant)[: self.MAX_REQUESTS // 2]:
                    del self._rid_tenant[k]
            self._rid_tenant[rid] = str(tenant)

    def tenant_of(self, rid: int) -> Optional[str]:
        """The tenant stamped for an in-flight request (None when the edge
        never stamped one) — how admit-time emit sites label events for
        requests they only know by rid."""
        with self._lock:
            return self._rid_tenant.get(rid)

    def _fold_tenant(self, tenant: str, r: Dict[str, float],
                     tokens: float) -> None:
        """Caller holds ``self._lock``."""
        roll = self._tenant_roll.get(tenant)
        if roll is None:
            if len(self._tenant_roll) >= self.MAX_TENANT_ROWS \
                    and tenant != self.OTHER_TENANT:
                cold = min(
                    (t for t in self._tenant_roll if t != self.OTHER_TENANT),
                    key=lambda t: (self._tenant_roll[t]["chip_s"], t),
                    default=None,
                )
                if cold is not None:
                    folded = self._tenant_roll.pop(cold)
                    other = self._tenant_roll.setdefault(
                        self.OTHER_TENANT,
                        {"requests": 0.0, "chip_s": 0.0, "useful_s": 0.0,
                         "tokens": 0.0, "cost_usd": 0.0},
                    )
                    for k in other:
                        other[k] += folded.get(k, 0.0)
            roll = self._tenant_roll[tenant] = {
                "requests": 0.0, "chip_s": 0.0, "useful_s": 0.0,
                "tokens": 0.0, "cost_usd": 0.0,
            }
        roll["requests"] += 1.0
        roll["chip_s"] += r["chip_s"]
        roll["useful_s"] += r["useful_s"]
        roll["tokens"] += float(tokens)
        if self.chip_hour_usd > 0:
            roll["cost_usd"] += r["chip_s"] / 3600.0 * self.chip_hour_usd

    def tenant_state(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant rollups (chip_s, cost_usd, tokens, goodput_frac) —
        the live source behind the ``rag_tenant_*`` goodput counters and
        the per-tenant conservation test (summed rollup chip_s tracks the
        ledger's attributed total, one dimension finer)."""
        with self._lock:
            out: Dict[str, Dict[str, float]] = {}
            for t, roll in self._tenant_roll.items():
                row = dict(roll)
                row["goodput_frac"] = round(
                    min(1.0, roll["useful_s"] / max(roll["chip_s"], 1e-30)), 6
                )
                out[t] = row
            return out

    def _apply(
        self,
        kind: str,
        dur_s: float,
        cat_s: Dict[str, float],
        per_request: Dict[int, float],  # rid -> useful weighted-lane share
        weight_total: float,
        flops: float,
        nbytes: float,
        tokens: float,
    ) -> Dict:
        """Fold one window into the rolling state and build the summary
        the caller journals (``flight.emit("goodput_window", **summary)``).
        ``cat_s`` values sum to ``dur_s`` exactly — the per-window
        conservation the tests pin."""
        rf = self.roofline
        mfu = rf.mfu(flops, dur_s)
        bw = rf.bw_util(nbytes, dur_s)
        bound = rf.classify(flops, nbytes)
        n_req = len(per_request)
        with self._lock:
            self._busy_s += dur_s
            for c, v in cat_s.items():
                self._cat_s[c] += v
            ks = self._kinds.setdefault(kind, {
                "busy_s": 0.0, "windows": 0.0, "tokens": 0.0,
                "mfu_w": 0.0, "bw_w": 0.0, "flops": 0.0, "bytes": 0.0,
            })
            ks["busy_s"] += dur_s
            ks["windows"] += 1
            ks["tokens"] += tokens
            ks["mfu_w"] += mfu * dur_s
            ks["bw_w"] += bw * dur_s
            ks["flops"] += flops
            ks["bytes"] += nbytes
            ks["bound"] = bound  # static per kind in practice
            if n_req:
                share = dur_s / n_req
                for rid, useful_w in per_request.items():
                    r = self._req(rid)
                    r["chip_s"] += share
                    if weight_total > 0:
                        r["useful_s"] += dur_s * useful_w / weight_total
                self._attributed_s += dur_s
        summary = {
            "kind": kind,
            "dur_ms": round(dur_s * 1e3, 4),
            "active": n_req,
            "tokens": int(tokens),
            "mfu": round(mfu, 6),
            "bw": round(bw, 6),
            "bound": bound,
        }
        for c, v in cat_s.items():
            if v > 0:
                summary[c] = round(v * 1e3, 4)
        return summary

    @staticmethod
    def _split(dur_s: float, weights: Dict[str, float]) -> Tuple[Dict[str, float], float]:
        """Weights → per-category chip-seconds summing to ``dur_s``."""
        total = sum(v for v in weights.values() if v > 0)
        if total <= 0:
            return {"padding_bubble": dur_s}, 0.0
        return (
            {c: dur_s * v / total for c, v in weights.items() if v > 0},
            total,
        )

    def record_decode(
        self,
        dur_s: float,
        batch: int,
        steps: int,
        kept: Dict[int, int],
        ctx_tokens: int = 0,
    ) -> Optional[Dict]:
        """One plain decode sync window: ``batch × steps`` token lanes;
        ``kept[rid]`` = tokens the host drain kept for each request that
        was active at dispatch. Everything else — inactive rows, post-EOS
        lanes, over-budget lanes — is padding bubble."""
        if not self.enabled or dur_s <= 0:
            return None
        lanes = max(1, batch * steps)
        useful = sum(kept.values())
        cat_s, total = self._split(dur_s, {
            "decode_useful": float(useful),
            "padding_bubble": float(lanes - useful),
        })
        rf = self.roofline
        flops = rf.flops_per_token * useful
        nbytes = steps * (rf.weight_bytes + ctx_tokens * rf.kv_bytes_per_token)
        with self._lock:
            self._useful_decode_tokens += useful
        return self._apply(
            "decode", dur_s, cat_s,
            {rid: float(n) for rid, n in kept.items()}, total,
            flops, nbytes, float(useful),
        )

    def record_verify(
        self,
        dur_s: float,
        batch: int,
        lanes_per_row: int,
        rows: Dict[int, Tuple[int, int, int]],  # rid -> (kept, offered, accepted)
        ctx_tokens: int = 0,
    ) -> Optional[Dict]:
        """One speculative verify window: ``batch × (K+1)`` lanes; a row's
        accepted+correction lanes are useful, drafted-but-rejected lanes
        are ``spec_rejected`` (real compute, discarded result), the rest is
        bubble. Per-row draft outcomes also accumulate into the request's
        speculation stats (``/generate`` timings satellite)."""
        if not self.enabled or dur_s <= 0:
            return None
        lanes = max(1, batch * lanes_per_row)
        useful = sum(k for k, _, _ in rows.values())
        rejected = sum(max(0, o - a) for _, o, a in rows.values())
        cat_s, total = self._split(dur_s, {
            "decode_useful": float(useful),
            "spec_rejected": float(rejected),
            "padding_bubble": float(lanes - useful - rejected),
        })
        rf = self.roofline
        flops = rf.flops_per_token * (useful + rejected)
        nbytes = rf.weight_bytes + ctx_tokens * rf.kv_bytes_per_token
        with self._lock:
            self._useful_decode_tokens += useful
            for rid, (_, offered, accepted) in rows.items():
                r = self._req(rid)
                r["spec_drafted"] += offered
                r["spec_accepted"] += accepted
                if offered > 0:
                    r["spec_windows"] += 1
        return self._apply(
            "verify", dur_s, cat_s,
            {rid: float(k) for rid, (k, _, _) in rows.items()}, total,
            flops, nbytes, float(useful),
        )

    def record_preempt_stall(
        self, dur_s: float, rids: Sequence[int], kind: str = "decode"
    ) -> Optional[Dict]:
        """Pool-pressure churn that ran no lanes but kept the scheduler
        busy: a window that preempted EVERY active row before dispatch
        (the step's early return, kind="decode"), or an admission chunk
        the exhausted pool bounced back to the queue (kind="prefill") —
        attributed wholesale to ``preempt_rework`` and split across the
        requests whose churn consumed it, so the conservation invariant
        survives pool storms. Zero flops/bytes: a stalled attempt
        honestly drags the MFU of the kind it cost."""
        if not self.enabled or dur_s <= 0:
            return None
        return self._apply(
            kind, dur_s, {"preempt_rework": dur_s},
            {rid: 0.0 for rid in rids}, 0.0, 0.0, 0.0, 0.0,
        )

    def record_prefill(
        self,
        dur_s: float,
        bucket: int,
        rows: Dict[int, int],  # rid -> computed prompt tokens
        rework: Optional[Set[int]] = None,
    ) -> Optional[Dict]:
        """One batched admission prefill: ``len(rows) × bucket`` lanes.
        Real prompt tokens are ``prefill_compute`` — unless the request is
        a preemption/reset resubmission, whose re-fed tokens were already
        computed once and count as ``preempt_rework`` (attributed exactly
        once, at the re-feeding admission); right-pad slack is bubble."""
        if not self.enabled or dur_s <= 0 or not rows:
            return None
        rework = rework or set()
        lanes = max(1, bucket * len(rows))
        computed = sum(n for rid, n in rows.items() if rid not in rework)
        refed = sum(n for rid, n in rows.items() if rid in rework)
        cat_s, total = self._split(dur_s, {
            "prefill_compute": float(computed),
            "preempt_rework": float(refed),
            "padding_bubble": float(lanes - computed - refed),
        })
        rf = self.roofline
        flops = rf.flops_per_token * (computed + refed)
        nbytes = rf.weight_bytes
        return self._apply(
            "prefill", dur_s, cat_s,
            {rid: (0.0 if rid in rework else float(n))
             for rid, n in rows.items()},
            total, flops, nbytes, float(computed + refed),
        )

    def record_prefill_px(
        self,
        dur_s: float,
        bucket: int,
        rid: int,
        computed: int,
        skipped: int,
        rework: bool = False,
    ) -> Optional[Dict]:
        """One prefixed admission: only the ``computed``-token suffix ran
        the model; the ``skipped`` prefix tokens were SERVED by a
        splice/scatter whose lane weight is the roofline's copy-vs-compute
        ratio (``prefill_skipped`` — the cheap residue of the prefill the
        cache avoided). Suffix pad is bubble."""
        if not self.enabled or dur_s <= 0:
            return None
        w_skip = self.roofline.splice_weight * max(0, skipped)
        key = "preempt_rework" if rework else "prefill_compute"
        cat_s, total = self._split(dur_s, {
            key: float(computed),
            "prefill_skipped": w_skip,
            "padding_bubble": float(max(0, bucket - computed)),
        })
        rf = self.roofline
        flops = rf.flops_per_token * computed
        nbytes = rf.weight_bytes + 2.0 * rf.kv_bytes_per_token * max(0, skipped)
        useful_w = (0.0 if rework else float(computed)) + w_skip
        return self._apply(
            "prefill_px", dur_s, cat_s, {rid: useful_w}, total,
            flops, nbytes, float(computed),
        )

    def record_oneshot(
        self,
        dur_s: float,
        bucket: int,
        batch: int,
        computed_tokens: int,
        decode_tokens: int,
        decode_steps: int,
        skipped: int = 0,
    ) -> Optional[Dict]:
        """One one-shot ``generate`` call (prefill + decode fused into one
        device program): the roofline model splits the measured duration
        into a prefill share (compute-bound: computed tokens ×
        t_compute) and a decode share (bandwidth-bound: steps × weight
        stream), then each sub-window decomposes like its continuous
        twin. Returns the summary plus ``chip_ms_per_row`` /
        ``goodput_frac`` for the caller's per-request timings."""
        if not self.enabled or dur_s <= 0:
            return None
        rf = self.roofline
        pad = max(0, batch * bucket - computed_tokens - skipped)
        t_pref = (
            computed_tokens * rf.t_compute_token + skipped * rf.t_copy_token
        )
        t_dec = max(0, decode_steps) * rf.weight_bytes / rf.peak_bytes
        est = t_pref + t_dec
        dur_p = dur_s * (t_pref / est) if est > 0 else dur_s
        dur_d = dur_s - dur_p
        cat_p, tot_p = self._split(dur_p, {
            "prefill_compute": float(computed_tokens),
            "prefill_skipped": rf.splice_weight * max(0, skipped),
            "padding_bubble": float(pad),
        })
        dec_lanes = max(1, batch * max(1, decode_steps))
        cat_d, tot_d = self._split(dur_d, {
            "decode_useful": float(decode_tokens),
            "padding_bubble": float(dec_lanes - decode_tokens),
        })
        cat_s = dict(cat_p)
        for c, v in cat_d.items():
            cat_s[c] = cat_s.get(c, 0.0) + v
        flops = rf.flops_per_token * (computed_tokens + decode_tokens)
        nbytes = (
            rf.weight_bytes * (1 + max(0, decode_steps))
            + 2.0 * rf.kv_bytes_per_token * max(0, skipped)
        )
        with self._lock:
            self._useful_decode_tokens += decode_tokens
        useful_s = (
            (dur_p * (cat_p.get("prefill_compute", 0.0)
                      + cat_p.get("prefill_skipped", 0.0)) / max(dur_p, 1e-30))
            + cat_d.get("decode_useful", 0.0)
        )
        summary = self._apply(
            "oneshot", dur_s, cat_s, {}, 0.0,
            flops, nbytes, float(computed_tokens + decode_tokens),
        )
        # the decode share alone, so the offline reconstruction counts the
        # same useful-decode-token total the live ledger does
        summary["decode_tokens"] = int(decode_tokens)
        summary["chip_ms_per_row"] = round(dur_s * 1e3 / max(batch, 1), 4)
        summary["goodput_frac"] = round(
            min(1.0, useful_s / max(dur_s, 1e-30)), 6
        )
        return summary

    def record_mixed(
        self,
        dur_s: float,
        batch: int,
        lanes: int,
        decode_kept: Dict[int, int],  # rid -> decode tokens the drain kept
        chunk_rows: Dict[int, int],  # rid -> prefill tokens fed this window
        rework: Optional[Set[int]] = None,
        ctx_tokens: int = 0,
    ) -> Optional[Dict]:
        """One UNIFIED ragged sync window (ISSUE 16): ``batch × lanes``
        lane grid, where each active decode row used exactly one real lane
        and each scheduled admission used its chunk's ``chunk_rows[rid]``
        lanes. Decode lanes that kept their token are ``decode_useful``;
        chunked-prefill lanes are ``prefill_compute`` — the whole point of
        the mixed window is that these lanes STOP being the
        ``padding_bubble`` the phase-separated scheduler burned — unless
        the admission is a preemption/reset resubmission
        (``preempt_rework``, attributed exactly once, same rule as
        ``record_prefill``). Everything else in the grid is bubble.
        Conservation is exact by ``_split``; only decode tokens feed the
        useful-decode throughput figure (prompt tokens never did)."""
        if not self.enabled or dur_s <= 0:
            return None
        rework = rework or set()
        grid = max(1, batch * max(1, lanes))
        useful = sum(decode_kept.values())
        computed = sum(
            n for rid, n in chunk_rows.items() if rid not in rework
        )
        refed = sum(n for rid, n in chunk_rows.items() if rid in rework)
        cat_s, total = self._split(dur_s, {
            "decode_useful": float(useful),
            "prefill_compute": float(computed),
            "preempt_rework": float(refed),
            "padding_bubble": float(grid - useful - computed - refed),
        })
        rf = self.roofline
        flops = rf.flops_per_token * (useful + computed + refed)
        nbytes = rf.weight_bytes + ctx_tokens * rf.kv_bytes_per_token
        with self._lock:
            self._useful_decode_tokens += useful
        per_request = {rid: float(n) for rid, n in decode_kept.items()}
        for rid, n in chunk_rows.items():
            per_request[rid] = per_request.get(rid, 0.0) + (
                0.0 if rid in rework else float(n)
            )
        summary = self._apply(
            "mixed", dur_s, cat_s, per_request, total,
            flops, nbytes, float(useful + computed + refed),
        )
        # the decode share alone (record_oneshot's convention), so the
        # offline reconstruction counts the same useful-decode-token total
        # the live ledger does
        summary["decode_tokens"] = int(useful)
        return summary

    # ------------------------------------------------------------------
    # per-request attribution (engine/scheduler thread)
    # ------------------------------------------------------------------
    def pop_request(self, rid: int,
                    tokens: float = 0.0) -> Optional[Dict[str, float]]:
        """A completed request's attributed figures (None when the ledger
        is disabled or the request never touched it): ``chip_ms``,
        ``goodput_frac``, ``cost_usd`` (when a chip-hour price is set),
        and the speculation stats when the request ever drafted. Feeds the
        /generate timings block; also stamps the completed-cost ring the
        per-query percentiles read. ``tokens`` (the delivered count, known
        only to the caller) feeds the tenant rollup when the request was
        ``note_tenant``-stamped."""
        with self._lock:
            r = self._requests.pop(rid, None)
            tenant = self._rid_tenant.pop(rid, None)
            if r is None:
                return None
            self._completed.append(r["chip_s"])
            if tenant is not None:
                self._fold_tenant(tenant, r, tokens)
        out = {
            "chip_ms": round(r["chip_s"] * 1e3, 4),
            "goodput_frac": round(
                min(1.0, r["useful_s"] / max(r["chip_s"], 1e-30)), 6
            ),
        }
        if self.chip_hour_usd > 0:
            out["cost_usd"] = r["chip_s"] / 3600.0 * self.chip_hour_usd
        if r["spec_windows"] > 0 or r["spec_drafted"] > 0:
            out["spec_drafted"] = int(r["spec_drafted"])
            out["spec_accepted"] = int(r["spec_accepted"])
            out["spec_accept_len_mean"] = round(
                r["spec_accepted"] / max(r["spec_windows"], 1.0), 4
            )
        return out

    # ------------------------------------------------------------------
    # reading (any thread)
    # ------------------------------------------------------------------
    def state(self) -> Dict:
        """A plain-dict snapshot of the rolling state — the mergeable/
        renderable form shared with the offline reconstruction."""
        with self._lock:
            return {
                "wall_s": time.monotonic() - self._t0,
                "busy_s": self._busy_s,
                "attributed_s": self._attributed_s,
                "useful_decode_tokens": self._useful_decode_tokens,
                "categories": dict(self._cat_s),
                "kinds": {k: dict(v) for k, v in self._kinds.items()},
                "request_chip_s": list(self._completed),
            }


# ---------------------------------------------------------------------------
# shared report plumbing (live ledger AND offline journal reconstruction)
# ---------------------------------------------------------------------------

def _empty_state() -> Dict:
    return {
        "wall_s": 0.0, "busy_s": 0.0, "attributed_s": 0.0,
        "useful_decode_tokens": 0.0,
        "categories": {c: 0.0 for c in WINDOW_CATEGORIES},
        "kinds": {}, "request_chip_s": [],
    }


def merge_states(states: Iterable[Dict]) -> Dict:
    """Sum several ledgers' states (the service serves one report over
    BOTH engines — continuous and one-shot). ``wall_s`` takes the max:
    the engines share one wall clock."""
    out = _empty_state()
    for st in states:
        out["wall_s"] = max(out["wall_s"], float(st.get("wall_s", 0.0)))
        out["busy_s"] += float(st.get("busy_s", 0.0))
        out["attributed_s"] += float(st.get("attributed_s", 0.0))
        out["useful_decode_tokens"] += float(
            st.get("useful_decode_tokens", 0.0)
        )
        for c, v in (st.get("categories") or {}).items():
            out["categories"][c] = out["categories"].get(c, 0.0) + float(v)
        for kind, ks in (st.get("kinds") or {}).items():
            dst = out["kinds"].setdefault(kind, {
                "busy_s": 0.0, "windows": 0.0, "tokens": 0.0,
                "mfu_w": 0.0, "bw_w": 0.0, "flops": 0.0, "bytes": 0.0,
            })
            for f in ("busy_s", "windows", "tokens", "mfu_w", "bw_w",
                      "flops", "bytes"):
                dst[f] += float(ks.get(f, 0.0))
            if "bound" in ks:
                dst["bound"] = ks["bound"]
        out["request_chip_s"].extend(st.get("request_chip_s") or [])
    return out


def state_from_events(events: Sequence[Dict]) -> Dict:
    """Rebuild the mergeable state from a flight journal's
    ``goodput_window`` (+ ``complete``) events — the offline half of the
    same-report contract (``flightview --goodput`` vs
    ``GET /debug/goodput``). Events carry per-window category chip-ms and
    precomputed mfu/bw, so no model config is needed offline."""
    st = _empty_state()
    t_lo = t_hi = None
    for e in events:
        t = e.get("t")
        if t is not None:
            t_lo = t if t_lo is None else min(t_lo, t)
            t_hi = t if t_hi is None else max(t_hi, t)
        etype = e.get("type")
        if etype == "complete":
            if "chip_ms" in e:
                st["request_chip_s"].append(float(e["chip_ms"]) / 1e3)
            continue
        if etype != "goodput_window":
            continue
        dur_s = float(e.get("dur_ms", 0.0)) / 1e3
        kind = e.get("kind", "decode")
        st["busy_s"] += dur_s
        if int(e.get("active", 0)) > 0:
            st["attributed_s"] += dur_s
        for c in WINDOW_CATEGORIES:
            if c in e:
                st["categories"][c] += float(e[c]) / 1e3
        ks = st["kinds"].setdefault(kind, {
            "busy_s": 0.0, "windows": 0.0, "tokens": 0.0,
            "mfu_w": 0.0, "bw_w": 0.0, "flops": 0.0, "bytes": 0.0,
        })
        ks["busy_s"] += dur_s
        ks["windows"] += 1
        ks["tokens"] += float(e.get("tokens", 0.0))
        ks["mfu_w"] += float(e.get("mfu", 0.0)) * dur_s
        ks["bw_w"] += float(e.get("bw", 0.0)) * dur_s
        if "bound" in e:
            ks["bound"] = e["bound"]
        if kind in ("decode", "verify"):
            st["useful_decode_tokens"] += float(e.get("tokens", 0.0))
        elif kind in ("oneshot", "mixed"):
            # both carry prefill AND decode lanes in one window; the
            # summary stamps the decode share separately
            st["useful_decode_tokens"] += float(e.get("decode_tokens", 0.0))
    if t_lo is not None:
        st["wall_s"] = max(st["busy_s"], float(t_hi) - float(t_lo))
    return st


def render_report(state: Dict, chip_hour_usd: float = 0.0) -> Dict:
    """The capacity picture the future disaggregation router consumes —
    ONE renderer for both sources (live ledger state, offline journal
    reconstruction), so ``GET /debug/goodput`` and ``flightview
    --goodput`` cannot drift apart."""
    busy = float(state.get("busy_s", 0.0))
    wall = max(float(state.get("wall_s", 0.0)), busy)
    idle = max(0.0, wall - busy)
    cats = {}
    for c in WINDOW_CATEGORIES:
        v = float(state.get("categories", {}).get(c, 0.0))
        cats[c] = {
            "chip_s": round(v, 6),
            "frac": round(v / busy, 6) if busy > 0 else 0.0,
        }
    cats["idle"] = {
        "chip_s": round(idle, 6),
        "frac": round(idle / wall, 6) if wall > 0 else 0.0,
    }
    kinds = {}
    for kind, ks in (state.get("kinds") or {}).items():
        kb = float(ks.get("busy_s", 0.0))
        kinds[kind] = {
            "windows": int(ks.get("windows", 0)),
            "busy_s": round(kb, 6),
            "tokens": int(ks.get("tokens", 0)),
            "mfu": round(float(ks.get("mfu_w", 0.0)) / kb, 6) if kb > 0 else 0.0,
            "bw_util": round(float(ks.get("bw_w", 0.0)) / kb, 6) if kb > 0 else 0.0,
            "bound": ks.get("bound", "unknown"),
        }
    price = max(0.0, float(chip_hour_usd))
    per_query: List[float] = [
        float(v) for v in state.get("request_chip_s") or []
    ]
    usd_per_s = price / 3600.0
    tokens = float(state.get("useful_decode_tokens", 0.0))
    wall_usd = wall * usd_per_s
    cost = {
        "chip_hour_usd": price,
        "wall_usd": round(wall_usd, 8),
        "busy_usd": round(busy * usd_per_s, 8),
        "tokens_per_usd": round(tokens / wall_usd, 2) if wall_usd > 0 else 0.0,
        "per_query_chip_ms": {
            "p50": round((_percentile(per_query, 0.50) or 0.0) * 1e3, 4),
            "p95": round((_percentile(per_query, 0.95) or 0.0) * 1e3, 4),
            "n": len(per_query),
        },
    }
    if price > 0:
        cost["per_query_usd"] = {
            "p50": round((_percentile(per_query, 0.50) or 0.0) * usd_per_s, 8),
            "p95": round((_percentile(per_query, 0.95) or 0.0) * usd_per_s, 8),
        }
    attributed = float(state.get("attributed_s", 0.0))
    return {
        "schema_version": 1,
        "wall_s": round(wall, 4),
        "busy_s": round(busy, 6),
        "idle_s": round(idle, 4),
        "busy_frac": round(busy / wall, 6) if wall > 0 else 0.0,
        "categories": cats,
        "kinds": kinds,
        "cost": cost,
        # live sanity mirror of the tested invariant: chip-seconds handed
        # to requests over chip-seconds windows with requests present
        "conservation": {
            "attributed_s": round(attributed, 6),
            "busy_s": round(busy, 6),
            "ratio": round(attributed / busy, 6) if busy > 0 else 1.0,
        },
    }
