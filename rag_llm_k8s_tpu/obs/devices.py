"""Per-device telemetry: HBM occupancy + prefix-cache residency gauges.

The prefix cache (PR 1) runs an HBM-budgeted LRU, and the continuous engine
parks multi-GB KV state per chip — but until now the scrape had no
per-device view, so an HBM-pressure eviction storm looked like generic
latency noise. These gauges label every family by device index:

- ``rag_device_hbm_bytes_in_use`` / ``rag_device_hbm_bytes_limit`` — read
  from ``device.memory_stats()`` at collect time (the live allocator view,
  zero writes on any hot path). CPU devices (and backends without the API)
  report **zero gracefully** — tier-1 runs on ``JAX_PLATFORMS=cpu`` and a
  scrape there must stay boring, not crash;
- ``rag_prefix_cache_device_bytes`` — the cache's resident KV attributed to
  the device(s) actually holding the planes (sharded planes split their
  bytes evenly across their device set), via
  :meth:`~rag_llm_k8s_tpu.engine.prefix_cache.PrefixCache.bytes_by_device`.

Registration is idempotent per registry (callback children just swap their
probe), and services that never enable the prefix cache still export the
family at zero so dashboards stay uniform across the fleet.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from rag_llm_k8s_tpu.obs import metrics as obs_metrics

__all__ = ["register_device_gauges", "local_devices"]


def local_devices() -> List:
    """``jax.local_devices()`` or [] when jax is absent/unusable — device
    telemetry must never be the thing that breaks an import."""
    try:
        import jax

        return list(jax.local_devices())
    except Exception:  # noqa: BLE001 — no jax, no devices, no gauges
        return []


def _memory_stat(device, key: str) -> float:
    """One allocator stat, 0.0 when unavailable (CPU backends return None
    or raise — the graceful-zero contract)."""
    if getattr(device, "platform", "") == "cpu":
        return 0.0
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — a probe must not 500 /metrics
        return 0.0
    if not stats:
        return 0.0
    return float(stats.get(key, 0.0))


def register_device_gauges(
    registry: obs_metrics.MetricsRegistry,
    prefix_bytes_fn: Optional[Callable[[], Dict[int, int]]] = None,
) -> int:
    """Register the per-device families on ``registry``; returns the device
    count. ``prefix_bytes_fn`` returns ``{device_id: bytes}`` for the
    prefix cache (None/empty → zeros, keeping the family present)."""
    devices = local_devices()
    use_fam = registry.labeled_gauge(
        "rag_device_hbm_bytes_in_use",
        "allocator bytes in use per device (0 on CPU/backends without "
        "memory_stats)",
    )
    lim_fam = registry.labeled_gauge(
        "rag_device_hbm_bytes_limit", "allocator byte limit per device"
    )
    pc_fam = registry.labeled_gauge(
        "rag_prefix_cache_device_bytes",
        "KV prefix-cache bytes resident per device",
    )
    fn = prefix_bytes_fn or (lambda: {})
    for d in devices:
        did = int(getattr(d, "id", 0))
        use_fam.labels_callback(
            lambda d=d: _memory_stat(d, "bytes_in_use"), device=str(did)
        )
        lim_fam.labels_callback(
            lambda d=d: _memory_stat(d, "bytes_limit"), device=str(did)
        )
        pc_fam.labels_callback(
            lambda did=did, fn=fn: float(fn().get(did, 0)), device=str(did)
        )
    return len(devices)
