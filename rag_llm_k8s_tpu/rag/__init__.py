"""RAG pipeline: chunking, PDF extraction, prompt assembly, retrieve-then-generate."""

from rag_llm_k8s_tpu.rag.chunking import split_text
from rag_llm_k8s_tpu.rag.prompt import assemble_context, assemble_prompt

__all__ = ["split_text", "assemble_context", "assemble_prompt"]
