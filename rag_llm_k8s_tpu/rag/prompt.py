"""Prompt assembly — byte parity with the reference's format, plus an
opt-in Llama-3.1 chat-template mode the reference lacks.

Reference format (/root/reference/llm/rag.py:163-169):
- context block: ``Document '{filename}' (chunk {chunk_id}, score: {d:.4f}): {text}\\n\\n``
  for the top-3 results of the k=5 search;
- full prompt: ``{SYSTEM_MESSAGE}\\n\\nContext: {context}\\n\\nUser: {q}\\n\\nChatbot:``
  (a plain string — the reference never applies Llama-3.1's chat template even
  though it serves Instruct weights; ``chat_template=True`` here fixes that
  while keeping the default identical for parity).
"""

from __future__ import annotations

from typing import List, Sequence

from rag_llm_k8s_tpu.core.config import SYSTEM_MESSAGE
from rag_llm_k8s_tpu.index.store import SearchResult


def assemble_context(results: Sequence[SearchResult], top_n: int = 3) -> str:
    context = ""
    for r in results[:top_n]:
        doc = r.metadata
        context += (
            f"Document '{doc.get('filename')}' (chunk {doc.get('chunk_id')}, "
            f"score: {r.distance:.4f}): {doc.get('text')}\n\n"
        )
    return context


def assemble_prompt(
    user_prompt: str,
    context: str,
    system_message: str = SYSTEM_MESSAGE,
    chat_template: bool = False,
) -> str:
    if not chat_template:
        return f"{system_message}\n\nContext: {context}\n\nUser: {user_prompt}\n\nChatbot:"
    # Llama-3.1 chat format (header tokens are plain text here; the tokenizer
    # maps them to special ids)
    return (
        "<|begin_of_text|><|start_header_id|>system<|end_header_id|>\n\n"
        f"{system_message}\n\nContext: {context}<|eot_id|>"
        "<|start_header_id|>user<|end_header_id|>\n\n"
        f"{user_prompt}<|eot_id|>"
        "<|start_header_id|>assistant<|end_header_id|>\n\n"
    )


def extract_answer(generated_text: str) -> str:
    """Parity with rag.py:174: the answer is what follows the last 'Chatbot:'."""
    return generated_text.split("Chatbot:")[-1].strip()
