"""Retrieval lookahead: overlap embed+KNN with decode, pre-stage KV.

Per-request serving used to be strictly sequential — retrieve → assemble →
prefill → decode — so every query paid the embed+KNN stage on its critical
path even while the device was busy decoding *other* requests (BENCH_r05
measured that stage at ~118-132 ms under load). TeleRAG shows lookahead
retrieval hides this latency entirely under sustained load; SIFT motivates
having the retrieved chunks' KV already resident before admission. This
module is the pipeline that does both:

- **Async retrieval executor**: a bounded worker pool whose workers submit
  into the service's EXISTING retrieve coalescer, so lookahead embeds batch
  with live traffic's and run concurrently with in-flight decode. The HTTP
  layer launches a request's retrieval the moment its body is parsed —
  BEFORE the admission gate can queue it — and the serving tail merely
  *joins* the already-launched future (``claim``/``join``). Under load the
  queue wait and other requests' decode hide the whole retrieval.
- **KV pre-staging**: the moment a retrieval resolves, a service-provided
  callback builds/refreshes the resolved chunks' segment KV into
  prefix-cache entries (``PrefixCache.stage``) — and, on a paged continuous
  engine, registers the chain's full pool blocks
  (``ContinuousEngine.prestage_prefix``) — so admission splices instead of
  prefilling. Staging is *ref-count-correct*: a speculation superseded
  before admission releases exactly the blocks nothing else consumed
  (``release_staged`` / ``release_prestaged``).
- **Multi-turn pipelining**: requests carrying a ``session_id`` speculate
  turn N+1's retrieval from the accumulating conversation state while turn
  N decodes (the service calls ``speculate`` right before its generate
  stage). Speculative launches are gated by a service headroom probe (pool
  ``admission_state`` + breaker + admission queue) so lookahead can never
  starve live traffic.

Futures are keyed by the exact retrieval text and always produce their
results through the same retrieval entry point the sequential path uses —
greedy output streams are byte-identical with lookahead on or off
(tests/test_lookahead.py; ``make lookahead-smoke``).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from rag_llm_k8s_tpu.obs import flight
from rag_llm_k8s_tpu.obs import metrics as obs_metrics
from rag_llm_k8s_tpu.resilience import faults

logger = logging.getLogger(__name__)

_WASTE_REASONS = ("superseded", "expired", "abandoned", "stale", "failed")
_SKIP_REASONS = ("headroom", "inflight", "shutdown")


class JoinTimeout(TimeoutError):
    """``join``'s OWN wait expired (the caller's deadline ran out at the
    join). Distinct from a worker-side error re-raised through ``join`` —
    including a worker-side ``TimeoutError`` from a bounded coalescer
    submit, which must take the inline-retrieval fallback path, not the
    caller's deadline (504) path."""


class RetrievalFuture:
    """One launched-ahead retrieval: resolves on an executor worker; the
    serving tail joins it. Carries the staging handle for whatever KV its
    resolution pre-staged, so a superseded speculation can release it."""

    __slots__ = (
        "key", "trigger", "session_id", "done", "result", "error",
        "t_launch", "index_gen", "staging", "claimed", "superseded",
        "waiters",
    )

    def __init__(self, key: str, trigger: str, session_id: Optional[str],
                 index_gen: int):
        self.key = key
        self.trigger = trigger  # "admission" | "session"
        self.session_id = session_id
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.t_launch = time.monotonic()
        self.index_gen = index_gen  # store size at launch (stale detection)
        self.staging = None  # opaque service handle (released when stale)
        self.claimed = False
        self.superseded = False
        # HTTP requests launched/deduped onto this future pre-admission —
        # each abandons on shed, and the future dies only when the LAST
        # one lets go (a shed duplicate must not strand the others)
        self.waiters = 0

    def resolved(self) -> bool:
        return self.done.is_set()


class LookaheadExecutor:
    """Bounded async retrieval pool + future registry + staging lifecycle.

    Thread-safe. All callbacks are service-provided:

    - ``retrieve_fn(text)`` — the blocking coalesced retrieval (the same
      entry point the sequential path uses: results are identical by
      construction);
    - ``prestage_fn(text, result)`` — build the resolved chunks' prefix KV,
      returning an opaque staging handle (or None);
    - ``release_fn(handle)`` — release a stale staging handle;
    - ``headroom_fn()`` — False while speculative work would pressure live
      traffic (pool headroom / breaker / admission queue);
    - ``index_gen_fn()`` — the store's live vector count: a future launched
      against an older index is stale and never served;
    - ``tier_stats_fn()`` — the prefix cache's tier counters (KV tiering,
      engine/tiering.py): the prestage path IS the cold-tier swap-in's
      prefetch trigger (``PrefixCache.stage(trigger="lookahead")`` performs
      any host→HBM swap-in on the worker thread, overlapped with the
      previous request's decode), and ``stats()`` folds those counters into
      the swap-in HIDE RATE the bench leg reports.
    """

    def __init__(
        self,
        config,
        retrieve_fn: Callable[[str], object],
        prestage_fn: Optional[Callable[[str, object], object]] = None,
        release_fn: Optional[Callable[[object], None]] = None,
        headroom_fn: Optional[Callable[[], bool]] = None,
        index_gen_fn: Optional[Callable[[], int]] = None,
        registry=None,
        tier_stats_fn: Optional[Callable[[], dict]] = None,
    ):
        self.config = config
        self.retrieve_fn = retrieve_fn
        self.prestage_fn = prestage_fn
        self.release_fn = release_fn
        self.headroom_fn = headroom_fn
        self.index_gen_fn = index_gen_fn or (lambda: 0)
        self.tier_stats_fn = tier_stats_fn
        self._lock = threading.Lock()
        self._futures: Dict[str, RetrievalFuture] = {}
        self._session_spec: Dict[str, RetrievalFuture] = {}
        self._inflight = 0  # launched, not yet resolved
        self._queue: "queue.Queue[Optional[RetrievalFuture]]" = queue.Queue()
        self._stop = threading.Event()
        # optional obs Counter — shutdown join timeouts (engine.batching)
        self.join_timeout_counter = None
        self.bind_metrics(
            registry if registry is not None else obs_metrics.default_registry()
        )
        self._workers = [
            threading.Thread(
                target=self._run, daemon=True, name=f"lookahead-{i}"
            )
            for i in range(max(1, int(config.max_workers)))
        ]
        for w in self._workers:
            w.start()
        # TTL enforcement must not depend on traffic: on a service that
        # goes quiet, the last speculations' staged KV (prefix entries +
        # registered pool blocks) must still expire on schedule — sweep()
        # on launches alone would hold them until the next request
        self._sweeper = threading.Thread(
            target=self._sweep_loop, daemon=True, name="lookahead-sweep"
        )
        self._sweeper.start()

    # -- observability ---------------------------------------------------
    def bind_metrics(self, registry) -> None:
        """Register the lookahead families (service rebinds, like engines)."""
        launched = registry.labeled_counter(
            "rag_lookahead_launched_total",
            "retrievals launched ahead of need (trigger: admission — "
            "pre-admission launch for an arrived request; session — "
            "speculative next-turn launch)",
        )
        self._m_launched = {
            t: launched.labels(trigger=t) for t in ("admission", "session")
        }
        joins = registry.labeled_counter(
            "rag_lookahead_joins_total",
            "serving-tail retrieve outcomes under lookahead (outcome: hit — "
            "future already resolved at join; late — join waited on a "
            "still-running future; miss — no future existed, retrieval ran "
            "inline)",
        )
        self._m_joins = {
            o: joins.labels(outcome=o) for o in ("hit", "late", "miss")
        }
        wasted = registry.labeled_counter(
            "rag_lookahead_wasted_total",
            "lookahead retrievals discarded unconsumed (reason: superseded "
            "| expired | abandoned | stale | failed)",
        )
        self._m_wasted = {r: wasted.labels(reason=r) for r in _WASTE_REASONS}
        skipped = registry.labeled_counter(
            "rag_lookahead_skipped_total",
            "lookahead launches refused before any work (reason: headroom "
            "— pool/breaker/queue pressure; inflight — speculation bound; "
            "shutdown)",
        )
        self._m_skipped = {r: skipped.labels(reason=r) for r in _SKIP_REASONS}
        self._m_prestaged = registry.counter(
            "rag_lookahead_prestaged_total",
            "resolved lookahead retrievals whose chunk KV was pre-staged "
            "into prefix-cache entries / pool blocks (under KV tiering "
            "this includes cold-tier host→HBM swap-ins performed off the "
            "critical path — the swap-in hide mechanism)",
        )
        self._m_prestage_released = registry.counter(
            "rag_lookahead_prestage_released_total",
            "stale pre-stagings released (every block nothing else "
            "consumed returned to its pool/budget)",
        )
        self._m_join_wait = registry.histogram(
            "rag_lookahead_launch_to_join_seconds",
            "launch-to-join latency of consumed lookahead futures (the "
            "retrieval time hidden off the critical path)",
            buckets=obs_metrics.REQUEST_BUCKETS,
        )
        registry.gauge(
            "rag_lookahead_inflight",
            "lookahead retrievals launched and not yet resolved",
            fn=lambda: float(self._inflight),
        )

    # -- launch / claim / join -------------------------------------------
    def launch(
        self, text: str, trigger: str = "admission",
        session_id: Optional[str] = None,
    ) -> Optional[RetrievalFuture]:
        """Start (or dedupe onto) a lookahead retrieval for ``text``.

        Non-blocking. Speculative (session) launches gate on the headroom
        probe; every launch gates on the in-flight bound. Returns the
        future, or None when the launch was skipped."""
        fut, _ = self.launch_tracked(text, trigger, session_id)
        return fut

    def launch_tracked(
        self, text: str, trigger: str = "admission",
        session_id: Optional[str] = None,
    ) -> Tuple[Optional[RetrievalFuture], bool]:
        """``launch`` + whether THIS call created the future. Every
        admission-trigger call (created or deduped) registers its request
        as a WAITER on the returned future; a shed request passes the
        future back to ``abandon``, and the future dies only when the last
        waiter lets go — shedding request B must never strand request A on
        an inline retrieval."""
        if not text or self._stop.is_set():
            if self._stop.is_set():
                self._m_skipped["shutdown"].inc()
            return None, False
        self.sweep()
        speculative = trigger == "session"
        if speculative and self.headroom_fn is not None:
            try:
                ok = bool(self.headroom_fn())
            except Exception:  # noqa: BLE001 — a broken probe must not launch
                ok = False
            if not ok:
                self._m_skipped["headroom"].inc()
                return None, False
        stale_spec: Optional[RetrievalFuture] = None
        created = False
        with self._lock:
            existing = self._futures.get(text)
            if existing is not None and not existing.superseded:
                # dedupe: one future per key
                fut = existing
                if not speculative:
                    fut.waiters += 1  # this request abandons on shed
                elif session_id is not None:
                    # the session's speculation slot follows the dedupe —
                    # its PREVIOUS speculation is replaced (and released)
                    # exactly like one replaced by a fresh launch
                    stale_spec = self._session_spec.get(session_id)
                    self._session_spec[session_id] = fut
            else:
                if self._inflight >= int(self.config.max_inflight):
                    self._m_skipped["inflight"].inc()
                    return None, False
                fut = RetrievalFuture(
                    text, trigger, session_id, int(self.index_gen_fn())
                )
                if not speculative:
                    fut.waiters = 1
                self._futures[text] = fut
                if speculative and session_id is not None:
                    stale_spec = self._session_spec.get(session_id)
                    self._session_spec[session_id] = fut
                self._inflight += 1
                created = True
            replace_ok = (
                stale_spec is not None and stale_spec is not fut
                # never kill a future admission requests still count on —
                # it dies via abandon/claim/TTL under its own rules
                and stale_spec.waiters == 0
            )
        if replace_ok:
            self._supersede(stale_spec, "superseded")
        if not created:
            return fut, False
        self._m_launched.get(trigger, self._m_launched["admission"]).inc()
        flight.emit("lookahead_launch", trigger=trigger)
        self._queue.put(fut)
        return fut, True

    def claim(self, text: str) -> Optional[RetrievalFuture]:
        """Take ownership of the future for ``text`` (the serving tail's
        side of the pipeline). A claimed future's staging is consumed — the
        claiming request's own prefix resolve bumps the use counters, so no
        release path will touch it. Returns None (counting a miss happens
        at the caller's discretion via ``note_miss``) when no live future
        matches or the index moved since launch."""
        with self._lock:
            fut = self._futures.pop(text, None)
            if fut is None:
                return None
            if fut.superseded:
                return None
            # claim under the SAME lock as the pop: a concurrent sweep
            # either sees claimed (keeps its hands off the staging) or
            # superseded the future first (we returned None above)
            fut.claimed = True
            if fut.session_id is not None:
                spec = self._session_spec.get(fut.session_id)
                if spec is fut:
                    del self._session_spec[fut.session_id]
        if fut.index_gen != int(self.index_gen_fn()):
            # launched against an older index snapshot: results are stale
            fut.claimed = False
            self._supersede(fut, "stale")
            return None
        return fut

    def join(self, fut: RetrievalFuture, timeout: Optional[float] = None):
        """Block until the claimed future resolves; return its result.

        Raises ``JoinTimeout`` when THIS wait expires (the caller's
        deadline path) and re-raises the worker-side error as-is (the
        caller falls back to inline retrieval — a failed speculation must
        never fail the request)."""
        hit = fut.resolved()
        if not fut.done.wait(timeout):
            raise JoinTimeout("lookahead retrieval did not resolve in time")
        if fut.error is not None:
            # failed joins stay out of the launch-to-join histogram — it
            # measures retrieval time hidden off the critical path, and a
            # ttl-sized error sample would skew the TTL-sizing signal
            self._m_wasted["failed"].inc()
            flight.emit("lookahead_waste", reason="failed")
            raise fut.error
        self._m_join_wait.observe(time.monotonic() - fut.t_launch)
        self._m_joins["hit" if hit else "late"].inc()
        flight.emit("lookahead_join", outcome="hit" if hit else "late")
        return fut.result

    def note_miss(self) -> None:
        """The serving tail ran retrieval inline (no future existed)."""
        self._m_joins["miss"].inc()
        flight.emit("lookahead_join", outcome="miss")

    def abandon(self, fut: Optional[RetrievalFuture]) -> None:
        """A launched future whose request was shed (admission 429/503):
        let go of it BY IDENTITY — never by key, which could alias a newer
        future re-created at the same text. The future dies (its staging
        released, the waste counted) only when the LAST pre-admission
        waiter lets go: a shed duplicate must not strand the concurrent
        requests still counting on it, and a session speculation a shed
        request merely deduped onto survives for the turn it was launched
        for (it expires by TTL like any other)."""
        if fut is None:
            return
        with self._lock:
            if fut.claimed or fut.superseded:
                return
            fut.waiters = max(0, fut.waiters - 1)
            if fut.waiters > 0 or fut.trigger != "admission":
                return
        self._supersede(fut, "abandoned")

    # -- session speculation ----------------------------------------------
    def speculate(self, session_id: str, text: str) -> Optional[RetrievalFuture]:
        """Launch the speculative next-turn retrieval for a session (called
        while the current turn decodes). Replaces — and releases — the
        session's previous speculation."""
        if not self.config.session_pipelining:
            return None
        return self.launch(text, trigger="session", session_id=session_id)

    # -- lifecycle ---------------------------------------------------------
    def _supersede(self, fut: RetrievalFuture, reason: str) -> None:
        """Mark a future dead and release its staging if it already
        resolved; an unresolved future releases on the worker thread the
        moment its (now pointless) retrieval completes. Idempotent: a
        future dies (and counts as waste) exactly once — an expired
        session speculation must not be counted again when its session's
        next turn replaces the stale registry entry. A CLAIMED future is
        never superseded: a sweep that snapshotted it right before a
        concurrent ``claim`` must not release the staging the claiming
        request is about to consume."""
        with self._lock:
            if fut.superseded or fut.claimed:
                return
            fut.superseded = True
            if self._futures.get(fut.key) is fut:
                del self._futures[fut.key]
            if (
                fut.session_id is not None
                and self._session_spec.get(fut.session_id) is fut
            ):
                del self._session_spec[fut.session_id]
        self._m_wasted[reason].inc()
        flight.emit("lookahead_waste", reason=reason)
        if fut.resolved():
            self._release(fut)

    def _release(self, fut: RetrievalFuture) -> None:
        with self._lock:
            # atomic take: the worker's end-of-run release and a concurrent
            # supersede (sweep/abandon/replace) must not both see the handle
            staging, fut.staging = fut.staging, None
        if staging is None or self.release_fn is None:
            return
        try:
            self.release_fn(staging)
            self._m_prestage_released.inc()
        except Exception:  # noqa: BLE001 — release must never propagate
            logger.exception("lookahead staging release failed")

    def _sweep_loop(self) -> None:
        """Periodic TTL sweep (also run opportunistically on every launch):
        half the TTL, clamped to [0.5s, 5s], so expiry lags the deadline by
        a bounded slice even with zero traffic."""
        interval = max(0.5, min(float(self.config.ttl_s) / 2.0, 5.0))
        while not self._stop.wait(interval):
            try:
                self.sweep()
            except Exception:  # noqa: BLE001 — the sweeper must survive
                logger.exception("lookahead sweep failed")

    def sweep(self, now: Optional[float] = None) -> int:
        """Expire unconsumed futures older than the TTL (their staging is
        released); opportunistically called on every launch. Returns the
        number expired."""
        now = time.monotonic() if now is None else now
        ttl = float(self.config.ttl_s)
        with self._lock:
            expired = [
                f for f in self._futures.values()
                if not f.claimed and now - f.t_launch > ttl
            ]
        for f in expired:
            self._supersede(f, "expired")
        return len(expired)

    def stats(self) -> Dict[str, float]:
        """Live hit/waste accounting for bench legs and tests."""
        hit = self._m_joins["hit"].value
        late = self._m_joins["late"].value
        miss = self._m_joins["miss"].value
        joins = hit + late + miss
        launched = sum(c.value for c in self._m_launched.values())
        wasted = sum(c.value for c in self._m_wasted.values())
        out = {
            "launched": launched,
            "joins": joins,
            "hit_rate": (hit / joins) if joins else 0.0,
            "overlap_rate": ((hit + late) / joins) if joins else 0.0,
            "waste_rate": (wasted / launched) if launched else 0.0,
            "prestaged": self._m_prestaged.value,
            "prestage_released": self._m_prestage_released.value,
        }
        if self.tier_stats_fn is not None:
            # KV-tiering swap-in hide rate: swap-ins the prestage path
            # performed off the critical path (trigger="lookahead") over
            # all swap-ins — 1.0 means every cold chunk was resident again
            # before its request's serving tail needed it
            try:
                ts = self.tier_stats_fn() or {}
            except Exception:  # noqa: BLE001 — stats must never fail a scrape
                ts = {}
            hidden = float(ts.get("swap_ins_lookahead", 0))
            demand = float(ts.get("swap_ins_demand", 0))
            out["swap_ins_hidden"] = hidden
            out["swap_ins_demand"] = demand
            out["swap_in_hide_rate"] = (
                hidden / (hidden + demand) if (hidden + demand) else 1.0
            )
        return out

    def shutdown(self) -> None:
        """Stop the workers and release every outstanding staging."""
        from rag_llm_k8s_tpu.engine.batching import _join_worker

        self._stop.set()
        for _ in self._workers:
            self._queue.put(None)
        for w in self._workers:
            _join_worker(w, self.join_timeout_counter, "lookahead")
        self._sweeper.join(timeout=6.0)  # wakes from _stop within interval
        with self._lock:
            leftovers = list(self._futures.values())
            self._futures.clear()
            self._session_spec.clear()
        # fail everything still QUEUED too: a claimed future is no longer
        # in the registry — the queue is the only place to find it, and a
        # request blocked in join() must fail fast, not stall out its
        # whole deadline (the scheduler/coalescer shutdown invariant)
        while True:
            try:
                queued = self._queue.get_nowait()
            except queue.Empty:
                break
            if queued is not None and queued not in leftovers:
                leftovers.append(queued)
        for f in leftovers:
            f.superseded = True
            if not f.resolved():
                f.error = RuntimeError("lookahead executor is shut down")
                f.done.set()
            self._release(f)

    # -- worker ------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            fut = self._queue.get()
            if fut is None:
                return
            try:
                if fut.superseded:
                    continue
                try:
                    faults.maybe_fail("lookahead_retrieve")
                    fut.result = self.retrieve_fn(fut.key)
                except BaseException as e:  # noqa: BLE001 — joiner falls back
                    fut.error = e
            finally:
                with self._lock:
                    self._inflight = max(0, self._inflight - 1)
                # resolve BEFORE pre-staging: a joiner must unblock the
                # moment results exist, not after the KV warm-up
                fut.done.set()
            if fut.error is not None:
                if fut.superseded:
                    self._release(fut)
                continue
            # The claimed/superseded reads here are deliberately lock-free
            # racy: a claim() landing mid-prestage leaves the future in the
            # SAME state as resolving before the claim — the claimer's own
            # prefix resolve consumes the staged entries (same text, same
            # chain: release_staged's use counters guard them) and a pool
            # registration it doesn't beat to admission stays as the
            # copy-free share, so a claimed future's staging is dropped by
            # contract, never released (see claim()). Only supersession
            # must release, and the post-attach re-check below covers a
            # supersede racing the attach.
            if (
                self.prestage_fn is not None
                and self.config.prestage_kv
                and not fut.claimed
                and not fut.superseded
            ):
                try:
                    staging = self.prestage_fn(fut.key, fut.result)
                except Exception:  # noqa: BLE001 — prestage is best-effort
                    logger.exception("lookahead prestage failed")
                    staging = None
                if staging is not None:
                    fut.staging = staging
                    self._m_prestaged.inc()
                    flight.emit("prestage", trigger=fut.trigger)
            if fut.superseded:
                self._release(fut)
