"""Word-window chunking — exact behavioral parity with the reference.

Reference (/root/reference/llm/rag.py:39-45): split on whitespace, windows of
``chunk_size`` words advancing by ``chunk_size - overlap`` (default 1000/200 ⇒
stride 800), last window may be short, joined back with single spaces.
"""

from __future__ import annotations

from typing import List


def split_text(text: str, chunk_size: int = 1000, overlap: int = 200) -> List[str]:
    if chunk_size <= overlap:
        raise ValueError(f"chunk_size ({chunk_size}) must exceed overlap ({overlap})")
    words = text.split()
    stride = chunk_size - overlap
    return [" ".join(words[i : i + chunk_size]) for i in range(0, len(words), stride)]
