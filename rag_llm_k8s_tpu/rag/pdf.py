"""Dependency-free PDF text extraction.

The reference extracts PDF text with PyPDF2 (/root/reference/llm/rag.py:9,48:
``PdfReader`` → per-page ``extract_text()`` concatenated with ``"\\n"``).
PyPDF2 is not available in this environment, so the framework carries its own
extractor (host-side Python — PDF parsing is I/O-bound, not TPU work; survey
§2b keeps it off-device on purpose).

Supported (covers the bundled Technology Radar corpus and ordinary text PDFs):
- classic ``N 0 obj`` bodies AND PDF-1.5+ compressed object streams (ObjStm);
- FlateDecode streams;
- page content streams: ``Tj``, ``'``, ``"``, ``TJ`` show-text operators with
  paren/hex strings, font switching via ``Tf``;
- per-font ``/ToUnicode`` CMaps (``bfchar``/``bfrange``) for both 1-byte
  simple fonts and 2-byte Identity-H Type0 fonts; latin-1 fallback otherwise.

Out of scope (rare in text corpora): LZW/DCT content, encryption, Type3 glyph
programs. Unknown constructs degrade to skipped bytes, never exceptions.
"""

from __future__ import annotations

import re
import zlib
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# object model + parser
# ---------------------------------------------------------------------------


class Ref:
    __slots__ = ("num",)

    def __init__(self, num: int):
        self.num = num

    def __repr__(self):
        return f"Ref({self.num})"


class Name(str):
    """A PDF /Name (distinct from string values)."""


_WS = b"\x00\t\n\x0c\r "
_DELIM = b"()<>[]{}/%"


def _skip_ws(data: bytes, pos: int) -> int:
    n = len(data)
    while pos < n:
        c = data[pos : pos + 1]
        if c in (b"%",):  # comment to EOL
            while pos < n and data[pos] not in b"\r\n":
                pos += 1
        elif c and c in _WS:
            pos += 1
        else:
            break
    return pos


def parse_object(data: bytes, pos: int):
    """Parse one PDF object at ``pos``; returns (value, new_pos)."""
    pos = _skip_ws(data, pos)
    c = data[pos : pos + 1]
    if c == b"<":
        if data[pos : pos + 2] == b"<<":
            return _parse_dict(data, pos)
        return _parse_hex_string(data, pos)
    if c == b"(":
        return _parse_literal_string(data, pos)
    if c == b"/":
        return _parse_name(data, pos)
    if c == b"[":
        return _parse_array(data, pos)
    if c in b"+-.0123456789":
        return _parse_number_or_ref(data, pos)
    if data[pos : pos + 4] == b"true":
        return True, pos + 4
    if data[pos : pos + 5] == b"false":
        return False, pos + 5
    if data[pos : pos + 4] == b"null":
        return None, pos + 4
    raise ValueError(f"unparseable object at {pos}: {data[pos:pos+20]!r}")


def _parse_dict(data: bytes, pos: int):
    pos += 2  # <<
    out: Dict[str, object] = {}
    while True:
        pos = _skip_ws(data, pos)
        if data[pos : pos + 2] == b">>":
            return out, pos + 2
        key, pos = _parse_name(data, pos)
        val, pos = parse_object(data, pos)
        out[str(key)] = val


def _parse_array(data: bytes, pos: int):
    pos += 1  # [
    out: List[object] = []
    while True:
        pos = _skip_ws(data, pos)
        if data[pos : pos + 1] == b"]":
            return out, pos + 1
        val, pos = parse_object(data, pos)
        out.append(val)


def _parse_name(data: bytes, pos: int):
    pos += 1  # /
    start = pos
    n = len(data)
    while pos < n and data[pos] not in _WS and data[pos] not in _DELIM:
        pos += 1
    raw = data[start:pos]
    # #xx escapes
    if b"#" in raw:
        raw = re.sub(rb"#([0-9A-Fa-f]{2})", lambda m: bytes([int(m.group(1), 16)]), raw)
    return Name(raw.decode("latin-1")), pos


def _parse_number_or_ref(data: bytes, pos: int):
    m = re.match(rb"[+-]?\d*\.?\d+", data[pos:])
    tok = m.group(0)
    end = pos + len(tok)
    if b"." not in tok:
        # lookahead for "G R" (indirect reference)
        m2 = re.match(rb"\s+(\d+)\s+R\b", data[end : end + 16])
        if m2:
            return Ref(int(tok)), end + m2.end()
        return int(tok), end
    return float(tok), end


def _parse_literal_string(data: bytes, pos: int):
    pos += 1  # (
    out = bytearray()
    depth = 1
    n = len(data)
    while pos < n:
        c = data[pos]
        if c == 0x5C:  # backslash
            pos += 1
            e = data[pos : pos + 1]
            mapping = {b"n": b"\n", b"r": b"\r", b"t": b"\t", b"b": b"\b", b"f": b"\x0c",
                       b"(": b"(", b")": b")", b"\\": b"\\"}
            if e in mapping:
                out += mapping[e]
                pos += 1
            elif (m := re.match(rb"[0-7]{1,3}", data[pos:])) is not None:  # octal
                out.append(int(m.group(0), 8) & 0xFF)
                pos += len(m.group(0))
            elif e in (b"\n", b"\r"):  # line continuation
                pos += 1
                if e == b"\r" and data[pos : pos + 1] == b"\n":
                    pos += 1
            else:
                pos += 1
        elif c == 0x28:  # (
            depth += 1
            out.append(c)
            pos += 1
        elif c == 0x29:  # )
            depth -= 1
            if depth == 0:
                return bytes(out), pos + 1
            out.append(c)
            pos += 1
        else:
            out.append(c)
            pos += 1
    return bytes(out), pos


def _parse_hex_string(data: bytes, pos: int):
    end = data.index(b">", pos)
    hexdata = re.sub(rb"[^0-9A-Fa-f]", b"", data[pos + 1 : end])
    if len(hexdata) % 2:
        hexdata += b"0"
    return bytes.fromhex(hexdata.decode("ascii")), end + 1


# ---------------------------------------------------------------------------
# document: objects, streams, ObjStm expansion
# ---------------------------------------------------------------------------

_OBJ_RE = re.compile(rb"(\d+)\s+(\d+)\s+obj\b")


class PdfDocument:
    def __init__(self, data: bytes):
        self.data = data
        self.objects: Dict[int, object] = {}
        self.streams: Dict[int, bytes] = {}
        self._scan_body()
        self._expand_object_streams()

    # -- raw scan -----------------------------------------------------------
    def _scan_body(self):
        data = self.data
        for m in _OBJ_RE.finditer(data):
            num = int(m.group(1))
            pos = m.end()
            try:
                val, pos = parse_object(data, pos)
            except (ValueError, IndexError):
                continue
            self.objects[num] = val
            pos = _skip_ws(data, pos)
            if data[pos : pos + 6] == b"stream":
                pos += 6
                if data[pos : pos + 2] == b"\r\n":
                    pos += 2
                elif data[pos : pos + 1] in (b"\n", b"\r"):
                    pos += 1
                length = val.get("Length") if isinstance(val, dict) else None
                if isinstance(length, Ref):
                    length = self.objects.get(length.num)
                if isinstance(length, int):
                    raw = data[pos : pos + length]
                else:
                    end = data.find(b"endstream", pos)
                    raw = data[pos:end].rstrip(b"\r\n")
                self.streams[num] = raw

    def _decode_stream(self, num: int) -> Optional[bytes]:
        raw = self.streams.get(num)
        obj = self.objects.get(num)
        if raw is None or not isinstance(obj, dict):
            return raw
        filt = obj.get("Filter")
        filters = [filt] if isinstance(filt, (Name, str)) else (filt or [])
        out = raw
        for f in filters:
            if str(f) == "FlateDecode":
                try:
                    out = zlib.decompress(out)
                except zlib.error:
                    try:
                        out = zlib.decompressobj().decompress(out)
                    except zlib.error:
                        return None
                parms = obj.get("DecodeParms")
                if isinstance(parms, dict) and parms.get("Predictor", 1) > 1:
                    out = _unpredict(out, parms)
            else:
                return None  # unsupported filter (DCT etc.)
        return out

    def _expand_object_streams(self):
        for num, obj in list(self.objects.items()):
            if not (isinstance(obj, dict) and str(obj.get("Type", "")) == "ObjStm"):
                continue
            payload = self._decode_stream(num)
            if payload is None:
                continue
            n = obj.get("N", 0)
            first = obj.get("First", 0)
            header = payload[:first].split()
            try:
                pairs = [
                    (int(header[2 * i]), int(header[2 * i + 1])) for i in range(n)
                ]
            except (ValueError, IndexError):
                continue
            for objnum, off in pairs:
                try:
                    val, _ = parse_object(payload, first + off)
                except (ValueError, IndexError):
                    continue
                # don't clobber a directly-parsed object (updates win in PDFs,
                # but body scan order already reflects the newest)
                self.objects.setdefault(objnum, val)

    # -- resolution ---------------------------------------------------------
    def deref(self, obj):
        seen = 0
        while isinstance(obj, Ref) and seen < 32:
            obj = self.objects.get(obj.num)
            seen += 1
        return obj

    def stream_for(self, obj) -> Optional[bytes]:
        if isinstance(obj, Ref):
            return self._decode_stream(obj.num)
        return None


def _unpredict(data: bytes, parms: dict) -> bytes:
    """PNG predictors (used by xref/ObjStm streams)."""
    predictor = parms.get("Predictor", 1)
    if predictor < 10:
        return data
    colors = parms.get("Colors", 1)
    bpc = parms.get("BitsPerComponent", 8)
    columns = parms.get("Columns", 1)
    rowlen = (colors * bpc * columns + 7) // 8
    stride = rowlen + 1
    out = bytearray()
    prev = bytearray(rowlen)
    for r in range(0, len(data) - stride + 1, stride):
        ft = data[r]
        row = bytearray(data[r + 1 : r + 1 + rowlen])
        if ft == 2:  # Up
            for i in range(rowlen):
                row[i] = (row[i] + prev[i]) & 0xFF
        elif ft == 1:  # Sub
            for i in range(1, rowlen):
                row[i] = (row[i] + row[i - 1]) & 0xFF
        out += row
        prev = row
    return bytes(out)


# ---------------------------------------------------------------------------
# fonts: ToUnicode CMaps
# ---------------------------------------------------------------------------

_BFCHAR_RE = re.compile(rb"beginbfchar(.*?)endbfchar", re.S)
_BFRANGE_RE = re.compile(rb"beginbfrange(.*?)endbfrange", re.S)
_HEX_RE = re.compile(rb"<([0-9A-Fa-f]+)>")


class FontDecoder:
    def __init__(self, two_byte: bool, cmap: Optional[Dict[int, str]]):
        self.two_byte = two_byte
        self.cmap = cmap

    def decode(self, raw: bytes) -> str:
        step = 2 if self.two_byte else 1
        out = []
        for i in range(0, len(raw) - step + 1, step):
            code = int.from_bytes(raw[i : i + step], "big")
            if self.cmap is not None:
                out.append(self.cmap.get(code, ""))
            else:
                out.append(chr(code) if code < 0x110000 else "")
        return "".join(out)


def parse_tounicode(cmap_bytes: bytes) -> Dict[int, str]:
    mapping: Dict[int, str] = {}

    def utf16(hexstr: bytes) -> str:
        b = bytes.fromhex(hexstr.decode("ascii"))
        try:
            return b.decode("utf-16-be")
        except UnicodeDecodeError:
            return ""

    for block in _BFCHAR_RE.findall(cmap_bytes):
        toks = _HEX_RE.findall(block)
        for i in range(0, len(toks) - 1, 2):
            mapping[int(toks[i], 16)] = utf16(toks[i + 1])
    for block in _BFRANGE_RE.findall(cmap_bytes):
        # two forms: <lo> <hi> <dst>  |  <lo> <hi> [<dst1> <dst2> ...]
        pos = 0
        entries = re.findall(rb"<([0-9A-Fa-f]+)>\s*<([0-9A-Fa-f]+)>\s*(\[[^\]]*\]|<[0-9A-Fa-f]+>)", block)
        for lo_h, hi_h, dst in entries:
            lo, hi = int(lo_h, 16), int(hi_h, 16)
            if dst.startswith(b"["):
                dsts = _HEX_RE.findall(dst)
                for off, d in enumerate(dsts):
                    if lo + off <= hi:
                        mapping[lo + off] = utf16(d)
            else:
                base_hex = dst.strip(b"<>")
                base_bytes = bytes.fromhex(base_hex.decode("ascii"))
                base = int.from_bytes(base_bytes[-2:], "big") if len(base_bytes) >= 2 else int(base_hex, 16)
                prefix = base_bytes[:-2]
                for code in range(lo, hi + 1):
                    val = base + (code - lo)
                    try:
                        s = (prefix + val.to_bytes(2, "big")).decode("utf-16-be")
                    except (UnicodeDecodeError, OverflowError):
                        s = ""
                    mapping[code] = s
        _ = pos
    return mapping


# ---------------------------------------------------------------------------
# content stream interpretation
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    rb"\((?:[^()\\]|\\.|\([^)]*\))*\)"  # literal string (1 nesting level fast path)
    rb"|<<|>>|<[0-9A-Fa-f\s]*>"
    rb"|\[|\]"
    rb"|/[^\s()<>\[\]{}/%]*"
    rb"|[+-]?\d*\.?\d+"
    rb"|[A-Za-z'\"*]+"
)


def _extract_page_text(content: bytes, fonts: Dict[str, FontDecoder]) -> str:
    out: List[str] = []
    stack: List[object] = []
    cur_font: Optional[FontDecoder] = None
    default = FontDecoder(two_byte=False, cmap=None)

    def show(raw: bytes):
        dec = (cur_font or default).decode(raw)
        if dec:
            out.append(dec)

    for m in _TOKEN_RE.finditer(content):
        tok = m.group(0)
        c = tok[:1]
        if c == b"(":
            val, _ = _parse_literal_string(tok, 0)
            stack.append(val)
        elif c == b"<" and tok != b"<<":
            val, _ = _parse_hex_string(tok, 0)
            stack.append(val)
        elif c == b"/":
            stack.append(Name(tok[1:].decode("latin-1")))
        elif c in b"+-.0123456789":
            stack.append(float(tok))
        elif tok == b"[":
            stack.append("[")
        elif tok == b"]":
            pass
        elif tok in (b"<<", b">>"):
            pass
        else:  # operator
            op = tok
            if op == b"Tf" and len(stack) >= 2:
                name = stack[-2]
                if isinstance(name, Name):
                    cur_font = fonts.get(str(name), cur_font)
            elif op == b"Tj" and stack and isinstance(stack[-1], bytes):
                show(stack[-1])
            elif op in (b"'", b'"'):
                if stack and isinstance(stack[-1], bytes):
                    out.append("\n")
                    show(stack[-1])
            elif op == b"TJ":
                # consume back to the matching "[" marker
                i = len(stack) - 1
                items: List[object] = []
                while i >= 0 and stack[i] != "[":
                    items.append(stack[i])
                    i -= 1
                for item in reversed(items):
                    if isinstance(item, bytes):
                        show(item)
                    elif isinstance(item, float) and item < -150:
                        out.append(" ")  # large negative kern ≈ word gap
                del stack[max(i, 0):]
            elif op in (b"Td", b"TD", b"T*", b"Tm", b"BT"):
                if out and not out[-1].endswith(("\n", " ")):
                    out.append("\n")
            stack.clear()  # every operator consumes its operands
    return "".join(out)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _pages_in_reading_order(doc: PdfDocument) -> List[dict]:
    """Walk the /Pages tree from the catalog (the spec's reading order, what
    PyPDF2's ``reader.pages`` yields); fall back to object-number order only
    if no catalog tree is parseable."""
    catalog = next(
        (
            obj
            for _, obj in sorted(doc.objects.items())
            if isinstance(obj, dict) and str(obj.get("Type", "")) == "Catalog"
        ),
        None,
    )
    pages: List[dict] = []
    seen: set = set()

    def walk(node_ref):
        if isinstance(node_ref, Ref):
            if node_ref.num in seen:  # cycle guard
                return
            seen.add(node_ref.num)
        node = doc.deref(node_ref)
        if not isinstance(node, dict):
            return
        t = str(node.get("Type", ""))
        if t == "Page":
            pages.append(node)
        elif t == "Pages" or "Kids" in node:
            kids = doc.deref(node.get("Kids")) or []
            for kid in kids:
                walk(kid)

    if catalog is not None:
        walk(catalog.get("Pages"))
    if not pages:  # fallback: no walkable tree
        pages = [
            obj
            for _, obj in sorted(doc.objects.items())
            if isinstance(obj, dict) and str(obj.get("Type", "")) == "Page"
        ]
    return pages


def extract_text(data: bytes) -> str:
    """Whole-document text: per-page text joined with ``"\\n"`` (parity with
    the reference's ``process_pdf``, rag.py:47-52)."""
    doc = PdfDocument(data)
    pages = _pages_in_reading_order(doc)
    texts: List[str] = []
    for page in pages:
        fonts = _page_fonts(doc, page)
        content = page.get("Contents")
        chunks: List[bytes] = []
        for ref in content if isinstance(content, list) else [content]:
            s = doc.stream_for(ref)
            if s:
                chunks.append(s)
        if not chunks:
            texts.append("")
            continue
        texts.append(_extract_page_text(b"\n".join(chunks), fonts))
    return "\n".join(texts) + ("\n" if texts else "")


def _page_fonts(doc: PdfDocument, page: dict) -> Dict[str, FontDecoder]:
    fonts: Dict[str, FontDecoder] = {}
    res = doc.deref(page.get("Resources"))
    if not isinstance(res, dict):
        return fonts
    fdict = doc.deref(res.get("Font"))
    if not isinstance(fdict, dict):
        return fonts
    for fname, fref in fdict.items():
        fobj = doc.deref(fref)
        if not isinstance(fobj, dict):
            continue
        subtype = str(fobj.get("Subtype", ""))
        two_byte = subtype == "Type0" and str(fobj.get("Encoding", "")) in (
            "Identity-H",
            "Identity-V",
        )
        cmap = None
        tu = fobj.get("ToUnicode")
        if tu is not None:
            cm_bytes = doc.stream_for(tu)
            if cm_bytes:
                cmap = parse_tounicode(cm_bytes)
        fonts[str(fname)] = FontDecoder(two_byte=two_byte, cmap=cmap)
    return fonts


def extract_text_from_file(path: str) -> str:
    with open(path, "rb") as f:
        return extract_text(f.read())
