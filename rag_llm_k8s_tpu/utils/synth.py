"""Synthetic HF-layout checkpoint writer (validation / benchmarks).

Writes a ``model-0000X-of-0000N.safetensors`` shard set with EXACTLY the
tensor names, dtypes and shapes of a real HF Llama checkpoint — the same
on-disk surface ``download_model.py`` stages into the model PVC
(/root/reference/llm/download_model.py:14-25) — so the streaming loader
(`models/loader.py`) and TP placement (`parallel/sharding.py`) can be proven
at true 8B geometry without the 16 GB download this environment cannot make
(zero egress). Tensors are zero-filled: the proof targets memory behavior,
dtype handling and sharding math, not numerics (covered by the tiny
round-trip parity tests).
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

import numpy as np

from rag_llm_k8s_tpu.core.config import LlamaConfig


def llama_tensor_specs(config: LlamaConfig) -> List[Tuple[str, Tuple[int, ...]]]:
    """(hf_name, shape) for every tensor of a Llama checkpoint, in the
    embed → layers → norm/lm_head order real shard indexes follow."""
    D, I = config.hidden_size, config.intermediate_size
    H, K, hd, V = config.num_heads, config.num_kv_heads, config.head_dim, config.vocab_size
    specs: List[Tuple[str, Tuple[int, ...]]] = [
        ("model.embed_tokens.weight", (V, D)),
    ]
    for i in range(config.num_layers):
        p = f"model.layers.{i}."
        specs += [
            (p + "self_attn.q_proj.weight", (H * hd, D)),
            (p + "self_attn.k_proj.weight", (K * hd, D)),
            (p + "self_attn.v_proj.weight", (K * hd, D)),
            (p + "self_attn.o_proj.weight", (D, H * hd)),
            (p + "mlp.gate_proj.weight", (I, D)),
            (p + "mlp.up_proj.weight", (I, D)),
            (p + "mlp.down_proj.weight", (D, I)),
            (p + "input_layernorm.weight", (D,)),
            (p + "post_attention_layernorm.weight", (D,)),
        ]
    specs.append(("model.norm.weight", (D,)))
    if not config.tie_word_embeddings:
        specs.append(("lm_head.weight", (V, D)))
    return specs


def write_synth_checkpoint(
    out_dir: str,
    config: LlamaConfig,
    n_shards: int = 4,
    dtype=None,
) -> List[str]:
    """Write a zero-filled ``n_shards``-file safetensors checkpoint for
    ``config`` (default dtype: bfloat16, like the staged Meta weights).
    Tensors are assigned to shards by cumulative byte budget, matching how
    real HF shard indexes split a model. Returns the shard paths."""
    import ml_dtypes
    from safetensors.numpy import save_file

    dtype = np.dtype(ml_dtypes.bfloat16) if dtype is None else np.dtype(dtype)
    specs = llama_tensor_specs(config)
    total = sum(int(np.prod(s)) * dtype.itemsize for _, s in specs)
    budget = -(-total // n_shards)

    os.makedirs(out_dir, exist_ok=True)
    paths: List[str] = []
    shard: Dict[str, np.ndarray] = {}
    used, shard_i = 0, 1

    def flush():
        nonlocal shard, used, shard_i
        if not shard:
            return
        path = os.path.join(
            out_dir, f"model-{shard_i:05d}-of-{n_shards:05d}.safetensors"
        )
        save_file(shard, path)
        paths.append(path)
        shard, used, shard_i = {}, 0, shard_i + 1

    for name, shape in specs:
        nbytes = int(np.prod(shape)) * dtype.itemsize
        if shard and used + nbytes > budget and shard_i < n_shards:
            flush()
        shard[name] = np.zeros(shape, dtype)
        used += nbytes
    flush()
    return paths
