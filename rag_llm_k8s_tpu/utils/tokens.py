"""Small token-sequence utilities shared across the serving stack."""

from __future__ import annotations

import re
from typing import Iterable, List, Optional, Sequence


def compile_special_re(special_tokens: Iterable[str]):
    """Longest-first escaped alternation matching literal special-token
    strings in raw text (HF AddedVocabulary extraction order), or ``None``
    when there are none."""
    toks = sorted(special_tokens, key=len, reverse=True)
    if not toks:
        return None
    return re.compile("|".join(re.escape(t) for t in toks))


def truncate_keep_eos(
    ids: Sequence[int], limit: int, eos_id: Optional[int]
) -> List[int]:
    """Cut ``ids`` to ``limit``, restoring the trailing EOS the encoder was
    trained to expect — a bare ``[:limit]`` slice drops it and skews
    CLS-pooled embeddings (bge-m3 inputs are ``</s>``-terminated)."""
    ids = list(ids)
    if len(ids) <= limit:
        return ids
    ids = ids[:limit]
    if eos_id is not None:
        ids[-1] = eos_id
    return ids
