"""Small token-sequence utilities shared across the serving stack."""

from __future__ import annotations

from typing import List, Optional, Sequence


def truncate_keep_eos(
    ids: Sequence[int], limit: int, eos_id: Optional[int]
) -> List[int]:
    """Cut ``ids`` to ``limit``, restoring the trailing EOS the encoder was
    trained to expect — a bare ``[:limit]`` slice drops it and skews
    CLS-pooled embeddings (bge-m3 inputs are ``</s>``-terminated)."""
    ids = list(ids)
    if len(ids) <= limit:
        return ids
    ids = ids[:limit]
    if eos_id is not None:
        ids[-1] = eos_id
    return ids
