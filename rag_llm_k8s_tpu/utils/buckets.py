"""Shape-bucketing helpers shared by the decode engine and the encoder runner
(one executable per bucket; requests pad to the next bucket)."""

from __future__ import annotations

from typing import Sequence


def bucket_len(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n, clamping to the largest."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def next_pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b
