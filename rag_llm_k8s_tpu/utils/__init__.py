"""Shared utilities."""

from rag_llm_k8s_tpu.utils.buckets import bucket_len, next_pow2

__all__ = ["bucket_len", "next_pow2"]
