"""Pallas TPU kernels (with XLA fallbacks): kNN top-k, flash attention."""

from rag_llm_k8s_tpu.ops.knn import knn_topk, knn_topk_pallas, knn_topk_xla

__all__ = ["knn_topk", "knn_topk_pallas", "knn_topk_xla"]
