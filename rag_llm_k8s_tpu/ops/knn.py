"""Brute-force L2 kNN as a fused Pallas TPU kernel — the faiss replacement.

The reference's retrieval is ``faiss.IndexFlatL2.search`` on CPU
(/root/reference/llm/rag.py:61,116): exact squared-L2 over all chunk
embeddings, k=5. Here the embedding matrix lives in HBM as ``[N, 1024]``;
one kernel fuses

    distance matmul (MXU)  →  running top-k selection (VPU, VMEM scratch)

over row blocks of the matrix, so candidate distances never round-trip to
HBM — only the final ``[Q, k]`` result leaves the chip (BASELINE.json
config #4: "faiss.IndexFlatL2 kNN as Pallas kernel over HBM-resident chunk
embeddings").

Grid layout: 1-D over row blocks (sequential on TPU), with the running
top-k carried in VMEM scratch across grid steps. Per block:
``d = ||q||² + ||e||² − 2·q·eᵀ`` (true squared L2, matching the scores the
reference prints into its context string, rag.py:165), then k rounds of
min/argmin/mask merge the block into the running top-k. k is tiny (5), so
selection is k VPU passes over ``[Q, k + BN]``.

Squared-L2 on unit vectors is monotone in cosine (2 − 2cos), so ranking
parity with the reference's normalized embeddings is exact.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BIG = 3.4e38  # +inf stand-in that survives arithmetic (python float: not traced)


def _knn_kernel(q_ref, e_ref, en_ref, vals_ref, idx_ref, top_v, top_i, *, block_n: int, k: int):
    """One grid step: merge a [BN, D] block of embeddings into the running top-k."""
    i = pl.program_id(0)
    n_blocks = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        top_v[:] = jnp.full_like(top_v, BIG)
        top_i[:] = jnp.full_like(top_i, -1)

    q = q_ref[:]  # [Q, D] fp32
    e = e_ref[:]  # [BN, D] fp32
    qn = jnp.sum(q * q, axis=1, keepdims=True)  # [Q, 1]
    dot = jax.lax.dot_general(
        q, e, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [Q, BN]
    d = qn + en_ref[0, :][None, :] - 2.0 * dot  # [Q, BN]; padded rows carry BIG norms

    base = i * block_n
    Q = d.shape[0]
    cand_v = jnp.concatenate([top_v[:], d], axis=1)  # [Q, k+BN]
    block_ids = base + jax.lax.broadcasted_iota(jnp.int32, (Q, block_n), 1)
    cand_i = jnp.concatenate([top_i[:], block_ids], axis=1)

    cols = jax.lax.broadcasted_iota(jnp.int32, cand_v.shape, 1)
    out_cols = jax.lax.broadcasted_iota(jnp.int32, (Q, k), 1)
    new_v = top_v[:]
    new_i = top_i[:]
    for j in range(k):  # k static and tiny: unrolled VPU passes
        am = jnp.argmin(cand_v, axis=1)  # [Q]
        hit = cols == am[:, None]
        # (.at[:, j].set would lower to scatter — unsupported in Mosaic;
        #  select on the static column index instead)
        new_v = jnp.where(out_cols == j, jnp.min(cand_v, axis=1)[:, None], new_v)
        new_i = jnp.where(
            out_cols == j, jnp.sum(jnp.where(hit, cand_i, 0), axis=1)[:, None], new_i
        )
        cand_v = jnp.where(hit, BIG, cand_v)
    top_v[:] = new_v
    top_i[:] = new_i

    @pl.when(i == n_blocks - 1)
    def _emit():
        vals_ref[:] = top_v[:]
        idx_ref[:] = top_i[:]


@functools.partial(jax.jit, static_argnames=("k", "block_n", "interpret"))
def knn_topk_pallas(
    queries: jax.Array,  # [Q, D] fp32
    embeddings: jax.Array,  # [N_pad, D] fp32, rows >= n_valid are arbitrary
    sq_norms: jax.Array,  # [1, N_pad] fp32, padded entries = BIG
    k: int = 5,
    block_n: int = 512,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused distance + top-k. ``N_pad`` must be a multiple of ``block_n``."""
    Q, D = queries.shape
    N = embeddings.shape[0]
    assert N % block_n == 0, (N, block_n)
    grid = (N // block_n,)
    return pl.pallas_call(
        functools.partial(_knn_kernel, block_n=block_n, k=k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((Q, D), lambda i: (0, 0)),
            pl.BlockSpec((block_n, D), lambda i: (i, 0)),
            pl.BlockSpec((1, block_n), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((Q, k), lambda i: (0, 0)),
            pl.BlockSpec((Q, k), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Q, k), jnp.float32),
            jax.ShapeDtypeStruct((Q, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((Q, k), jnp.float32),
            pltpu.VMEM((Q, k), jnp.int32),
        ],
        interpret=interpret,
    )(queries, embeddings, sq_norms)


@functools.partial(jax.jit, static_argnames=("k",))
def knn_topk_xla(
    queries: jax.Array,  # [Q, D]
    embeddings: jax.Array,  # [N_pad, D]
    sq_norms: jax.Array,  # [1, N_pad]
    k: int = 5,
) -> Tuple[jax.Array, jax.Array]:
    """Pure-XLA reference/fallback (CPU tests, numerics oracle)."""
    qn = jnp.sum(queries * queries, axis=1, keepdims=True)
    d = qn + sq_norms - 2.0 * (queries @ embeddings.T)
    neg, idx = jax.lax.top_k(-d, k)
    return -neg, idx.astype(jnp.int32)


def knn_topk(
    queries: jax.Array,
    embeddings: jax.Array,
    sq_norms: jax.Array,
    k: int = 5,
    block_n: int = 512,
) -> Tuple[jax.Array, jax.Array]:
    """Backend dispatch: Pallas on TPU, XLA elsewhere."""
    if jax.default_backend() == "tpu" and embeddings.shape[0] % block_n == 0:
        return knn_topk_pallas(queries, embeddings, sq_norms, k=k, block_n=block_n)
    return knn_topk_xla(queries, embeddings, sq_norms, k=k)
