"""Flash attention (prefill) as a Pallas TPU kernel.

The torch path this replaces materializes full [S, S] attention matrices on
CPU inside ``model.generate`` (/root/reference/llm/rag.py:172). Here the
prefill attention runs blockwise: per (head, q-block), K/V blocks stream
through VMEM while a running (max, sum, accumulator) softmax keeps memory at
O(block²) — the flash-attention recurrence, written for the MXU/VPU split
(matmuls on the MXU via ``jax.lax.dot_general`` with fp32 accumulation,
renormalization on the VPU).

Masking model matches the serving engine's left-padded batches: causal over
global positions plus a per-row valid window ``[kv_start, kv_len)`` delivered
through scalar prefetch (SMEM) — no [S, S] bias array ever exists.

GQA is handled by index mapping: query head h reads K/V head ``h // G``
directly from HBM; K/V are never repeated in memory.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_kernel(
    kv_start_ref,  # SMEM [B]
    kv_len_ref,  # SMEM [B]
    q_ref,  # [1, bq, hd]
    k_ref,  # [1, bk, hd]
    v_ref,  # [1, bk, hd]
    o_ref,  # [1, bq, hd]
    m_scr,  # VMEM [bq, 1]
    l_scr,  # VMEM [bq, 1]
    acc_scr,  # VMEM [bq, hd]
    *,
    bq: int,
    bk: int,
    scale: float,
    causal: bool,
    num_heads: int,
):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    b = bh // num_heads

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal skip: a K block strictly above this Q block's diagonal is fully
    # masked — skip its matmuls entirely (halves causal prefill work)
    live = (kj * bk <= qi * bq + bq - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]

        q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        ok = (k_pos >= kv_start_ref[b]) & (k_pos < kv_len_ref[b])
        if causal:
            ok = ok & (k_pos <= q_pos)
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[:]  # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # explicit zero for masked entries: when a whole row is masked both s
        # and m_new sit at NEG_INF and exp(s - m_new) would be 1, polluting
        # l/acc with mean(V); the mask multiply makes such rows emit zeros
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)  # [bq, bk]
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(kj == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:], 1e-30)  # fully-masked rows -> 0, not NaN
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "bq", "bk", "interpret")
)
def flash_attention(
    q: jax.Array,  # [B, Sq, H, hd]
    k: jax.Array,  # [B, Sk, K, hd]
    v: jax.Array,  # [B, Sk, K, hd]
    kv_start: Optional[jax.Array] = None,  # [B] int32 (left-pad offset)
    kv_len: Optional[jax.Array] = None,  # [B] int32 (valid frontier)
    causal: bool = True,
    bq: int = 128,
    bk: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Blockwise fused attention; returns ``[B, Sq, H, hd]`` in q's dtype."""
    B, Sq, H, hd = q.shape
    _, Sk, K, _ = k.shape
    G = H // K
    bq = min(bq, Sq)
    bk = min(bk, Sk)
    assert Sq % bq == 0 and Sk % bk == 0, (Sq, bq, Sk, bk)
    if kv_start is None:
        kv_start = jnp.zeros((B,), jnp.int32)
    if kv_len is None:
        kv_len = jnp.full((B,), Sk, jnp.int32)

    # [B, S, H, hd] -> [B*H, S, hd] rows; kv head for query head h is h // G
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, Sq, hd)
    kt = k.transpose(0, 2, 1, 3).reshape(B * K, Sk, hd)
    vt = v.transpose(0, 2, 1, 3).reshape(B * K, Sk, hd)

    grid = (B * H, Sq // bq, Sk // bk)

    def kv_index(bh, qi, kj, *scalar_refs):
        return ((bh // H) * K + (bh % H) // G, kj, 0)

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            bq=bq,
            bk=bk,
            scale=hd**-0.5,
            causal=causal,
            num_heads=H,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, hd), lambda bh, qi, kj, *s_: (bh, qi, 0)),
                pl.BlockSpec((1, bk, hd), kv_index),
                pl.BlockSpec((1, bk, hd), kv_index),
            ],
            out_specs=pl.BlockSpec((1, bq, hd), lambda bh, qi, kj, *s_: (bh, qi, 0)),
            scratch_shapes=[
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, hd), q.dtype),
        interpret=interpret,
    )(kv_start.astype(jnp.int32), kv_len.astype(jnp.int32), qt, kt, vt)

    return out.reshape(B, H, Sq, hd).transpose(0, 2, 1, 3)


def attention_xla(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_start: Optional[jax.Array] = None,
    kv_len: Optional[jax.Array] = None,
    causal: bool = True,
) -> jax.Array:
    """Dense XLA reference (oracle for the kernel; fallback off-TPU)."""
    B, Sq, H, hd = q.shape
    _, Sk, K, _ = k.shape
    G = H // K
    qg = q.reshape(B, Sq, K, G, hd)
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32)
    s = s * (hd**-0.5)
    q_pos = jnp.arange(Sq)
    k_pos = jnp.arange(Sk)
    ok = jnp.ones((B, Sq, Sk), bool)
    if kv_start is not None:
        ok = ok & (k_pos[None, None, :] >= kv_start[:, None, None])
    if kv_len is not None:
        ok = ok & (k_pos[None, None, :] < kv_len[:, None, None])
    if causal:
        ok = ok & (k_pos[None, None, :] <= q_pos[None, :, None])
    s = jnp.where(ok[:, None, None, :, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # rows with no valid key: softmax of all-NEG_INF is uniform — zero it so
    # pad rows contribute nothing downstream (matches the fused kernels)
    p = jnp.where(ok[:, None, None, :, :], p, 0.0)
    o = jnp.einsum("bkgqs,bskd->bqkgd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o.reshape(B, Sq, H, hd).astype(q.dtype)
