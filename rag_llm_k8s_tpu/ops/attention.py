"""Flash attention (prefill) as a Pallas TPU kernel.

The torch path this replaces materializes full [S, S] attention matrices on
CPU inside ``model.generate`` (/root/reference/llm/rag.py:172). Here the
prefill attention runs blockwise: per (head, q-block), K/V blocks stream
through VMEM while a running (max, sum, accumulator) softmax keeps memory at
O(block²) — the flash-attention recurrence, written for the MXU/VPU split
(matmuls on the MXU via ``jax.lax.dot_general`` with fp32 accumulation,
renormalization on the VPU).

Masking model matches the serving engine's left-padded batches: causal over
global positions plus a per-row valid window ``[kv_start, kv_len)`` delivered
through scalar prefetch (SMEM) — no [S, S] bias array ever exists.

GQA is handled by index mapping: query head h reads K/V head ``h // G``
directly from HBM; K/V are never repeated in memory.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fit_block(n: int, pref: int) -> int:
    """Largest block ≤ ``pref`` that tiles ``n`` exactly (halves until it
    divides; terminates at 1)."""
    b = min(pref, n)
    while n % b:
        b //= 2
    return b


def _flash_kernel(
    kv_start_ref,  # SMEM [B]
    kv_len_ref,  # SMEM [B]
    q_ref,  # [1, bq, hd]
    k_ref,  # [1, bk, hd]
    v_ref,  # [1, bk, hd]
    o_ref,  # [1, bq, hd]
    m_scr,  # VMEM [bq, 1]
    l_scr,  # VMEM [bq, 1]
    acc_scr,  # VMEM [bq, hd]
    *,
    bq: int,
    bk: int,
    scale: float,
    causal: bool,
    num_heads: int,
):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    b = bh // num_heads

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # block skip: fully-masked K blocks do no work — strictly above the
    # causal diagonal (halves causal prefill), entirely inside the left-pad
    # region (< kv_start), or entirely past the valid frontier (>= kv_len)
    overlap = (kj * bk + bk > kv_start_ref[b]) & (kj * bk < kv_len_ref[b])
    live = (overlap & (kj * bk <= qi * bq + bq - 1)) if causal else overlap

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        # zero K/V rows outside the valid window BEFORE any matmul: cache
        # slots past the frontier may be uninitialized device memory, and a
        # NaN there survives even a zero-weight product (0 * NaN = NaN)
        cpos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bk, 1), 0)
        cok = (cpos >= kv_start_ref[b]) & (cpos < kv_len_ref[b])
        k = jnp.where(cok, k, 0)
        v = jnp.where(cok, v, 0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]

        q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        ok = (k_pos >= kv_start_ref[b]) & (k_pos < kv_len_ref[b])
        if causal:
            ok = ok & (k_pos <= q_pos)
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[:]  # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # explicit zero for masked entries: when a whole row is masked both s
        # and m_new sit at NEG_INF and exp(s - m_new) would be 1, polluting
        # l/acc with mean(V); the mask multiply makes such rows emit zeros
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)  # [bq, bk]
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(kj == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:], 1e-30)  # fully-masked rows -> 0, not NaN
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "bq", "bk", "interpret")
)
def flash_attention(
    q: jax.Array,  # [B, Sq, H, hd]
    k: jax.Array,  # [B, Sk, K, hd]
    v: jax.Array,  # [B, Sk, K, hd]
    kv_start: Optional[jax.Array] = None,  # [B] int32 (left-pad offset)
    kv_len: Optional[jax.Array] = None,  # [B] int32 (valid frontier)
    causal: bool = True,
    bq: int = 1024,
    bk: int = 1024,
    interpret: bool = False,
) -> jax.Array:
    """Blockwise fused attention; returns ``[B, Sq, H, hd]`` in q's dtype.

    Default blocks are deliberately coarse (1024×1024): the TPU grid runs
    sequentially, so per-step overhead is amortized by doing more MXU work
    per step. Swept on v5e at the 4096-token serving prefill: 1024×1024
    beats the earlier 256×512 by 36-40% (the [bq, bk] fp32 score/prob
    temporaries dominate VMEM at ~4 MB each — 2048-wide blocks overflow the
    16 MB scoped limit and fail to compile). Blocks shrink (halving) until
    they tile the sequence exactly, so any power-of-two length works."""
    B, Sq, H, hd = q.shape
    _, Sk, K, _ = k.shape
    G = H // K
    bq = _fit_block(Sq, bq)
    bk = _fit_block(Sk, bk)
    assert Sq % bq == 0 and Sk % bk == 0, (Sq, bq, Sk, bk)
    if kv_start is None:
        kv_start = jnp.zeros((B,), jnp.int32)
    if kv_len is None:
        kv_len = jnp.full((B,), Sk, jnp.int32)

    # [B, S, H, hd] -> [B*H, S, hd] rows; kv head for query head h is h // G
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, Sq, hd)
    kt = k.transpose(0, 2, 1, 3).reshape(B * K, Sk, hd)
    vt = v.transpose(0, 2, 1, 3).reshape(B * K, Sk, hd)

    grid = (B * H, Sq // bq, Sk // bk)

    def kv_index(bh, qi, kj, *scalar_refs):
        return ((bh // H) * K + (bh % H) // G, kj, 0)

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            bq=bq,
            bk=bk,
            scale=hd**-0.5,
            causal=causal,
            num_heads=H,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, hd), lambda bh, qi, kj, *s_: (bh, qi, 0)),
                pl.BlockSpec((1, bk, hd), kv_index),
                pl.BlockSpec((1, bk, hd), kv_index),
            ],
            out_specs=pl.BlockSpec((1, bq, hd), lambda bh, qi, kj, *s_: (bh, qi, 0)),
            scratch_shapes=[
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, hd), q.dtype),
        interpret=interpret,
    )(kv_start.astype(jnp.int32), kv_len.astype(jnp.int32), qt, kt, vt)

    return out.reshape(B, H, Sq, hd).transpose(0, 2, 1, 3)


def _decode_kernel(
    layer_ref,  # SMEM [1]
    kv_start_ref,  # SMEM [B]
    kv_len_ref,  # SMEM [B]
    q_ref,  # [1, K, G, hd]
    k_ref,  # [1, 1, K, bk, hd]
    v_ref,  # [1, 1, K, bk, hd]
    o_ref,  # [1, K, G, hd]
    m_scr,  # VMEM [K, G, 1]
    l_scr,  # VMEM [K, G, 1]
    acc_scr,  # VMEM [K, G, hd]
    *,
    bk: int,
    scale: float,
):
    b = pl.program_id(0)
    kj = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # skip blocks entirely outside the row's valid [kv_start, kv_len) window
    blk_lo = kj * bk
    live = (blk_lo < kv_len_ref[b]) & (blk_lo + bk > kv_start_ref[b])

    @pl.when(live)
    def _compute():
        q = q_ref[0]  # [K, G, hd]
        k = k_ref[0, 0]  # [K, bk, hd]
        v = v_ref[0, 0]
        # zero K/V rows outside the valid window BEFORE any matmul: cache
        # slots past the frontier may be uninitialized device memory, and a
        # NaN there survives even a zero-weight product (0 * NaN = NaN)
        rpos = blk_lo + jax.lax.broadcasted_iota(
            jnp.int32, (k.shape[0], k.shape[1], 1), 1
        )
        rok = (rpos >= kv_start_ref[b]) & (rpos < kv_len_ref[b])
        k = jnp.where(rok, k, 0)
        v = jnp.where(rok, v, 0)
        # one batched dot over all kv heads: [K, G, hd] x [K, bk, hd] -> [K, G, bk]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * scale

        k_pos = blk_lo + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        ok = (k_pos >= kv_start_ref[b]) & (k_pos < kv_len_ref[b])
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=2, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(kj == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _decode_block(T: int, bk: int) -> int:
    """Largest K/V block ≤ ``bk`` that tiles ``T`` exactly: prefer the coarse
    candidates (more MXU work per sequential grid step), else the largest
    divisor of ``T`` that fits — any caller-supplied ``bk`` works."""
    if T <= bk:
        return T
    for cand in (512, 384, 256, 128):
        if cand <= bk and T % cand == 0:
            return cand
    return max(d for d in range(1, min(bk, T) + 1) if T % d == 0)


@functools.partial(jax.jit, static_argnames=("bk", "interpret"))
def decode_attention(
    q: jax.Array,  # [B, 1, H, hd] — the single fresh query token
    k_cache: jax.Array,  # [L, B, K, T, hd] — FULL stacked head-major cache
    v_cache: jax.Array,  # [L, B, K, T, hd]
    kv_start: jax.Array,  # [B] int32: first valid cache slot (left-pad offset)
    kv_len: jax.Array,  # [B] int32: valid frontier (exclusive)
    layer: jax.Array,  # [] or [1] int32: which layer's cache to attend over
    bk: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Fused single-token decode attention over the KV cache.

    Replaces the reference's per-step torch attention inside ``model.generate``
    (/root/reference/llm/rag.py:172). The kernel reads ITS OWN layer straight
    out of the full stacked cache — ``layer`` rides scalar prefetch into the
    block index map, so no per-layer slice of the multi-GB cache is ever
    materialized. One grid cell per batch row: all K kv heads' blocks stream
    together (one batched MXU dot per block — the grid stays coarse so
    per-step kernel overhead never dominates the bandwidth-bound cache scan),
    with the flash recurrence across blocks; blocks outside
    ``[kv_start, kv_len)`` are compute-skipped. The ``[.., K, T, hd]`` layout
    makes every block K contiguous ``(bk, hd)`` slabs — tiled exactly for the
    VPU/MXU, no transposition of cache memory ever happens.
    """
    B, S, H, hd = q.shape
    assert S == 1, f"decode_attention is single-token (got S={S})"
    L, _, K, T, _ = k_cache.shape
    G = H // K
    req_bk = bk
    bk = _decode_block(T, bk)
    assert T % bk == 0, (T, bk)
    if not interpret and bk % 16:
        # a (bk, hd) block's second-to-minor dim must meet Mosaic's 16-row
        # bf16 tile on real hardware; fail actionably instead of opaquely
        raise ValueError(
            f"cache length T={T} only tiles into blocks of {bk} ≤ bk={req_bk}: "
            "pad T to a multiple of 128 — the engine rounds cache lengths for this"
        )

    qh = q.reshape(B, K, G, hd)
    grid = (B, T // bk)

    def kv_index(b, kj, layer_ref, *s_):
        return (layer_ref[0], b, 0, kj, 0)

    out = pl.pallas_call(
        functools.partial(_decode_kernel, bk=bk, scale=hd**-0.5),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, K, G, hd), lambda b, kj, *s_: (b, 0, 0, 0)),
                pl.BlockSpec((1, 1, K, bk, hd), kv_index),
                pl.BlockSpec((1, 1, K, bk, hd), kv_index),
            ],
            out_specs=pl.BlockSpec((1, K, G, hd), lambda b, kj, *s_: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((K, G, 1), jnp.float32),
                pltpu.VMEM((K, G, 1), jnp.float32),
                pltpu.VMEM((K, G, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, K, G, hd), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        kv_start.astype(jnp.int32),
        kv_len.astype(jnp.int32),
        qh,
        k_cache,
        v_cache,
    )

    return out.reshape(B, 1, H, hd)


def _chunk_kernel(
    layer_ref,  # SMEM [1] (consumed by the index maps)
    wi_ref,  # SMEM [1]: write_index — global cache slot of query 0
    kv_start_ref,  # SMEM [B]
    kv_len_ref,  # SMEM [B]
    q_ref,  # [1, bq, hd]
    k_ref,  # [1, 1, 1, bk, hd]
    v_ref,  # [1, 1, 1, bk, hd]
    o_ref,  # [1, bq, hd]
    m_scr,  # VMEM [bq, 1]
    l_scr,  # VMEM [bq, 1]
    acc_scr,  # VMEM [bq, hd]
    *,
    bq: int,
    bk: int,
    scale: float,
    num_heads: int,
):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    b = bh // num_heads
    wi = wi_ref[0]

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # block skip: K blocks inside the pad region / past the frontier /
    # strictly above the OFFSET causal diagonal (query t sits at global
    # cache slot wi + t) do no work
    q_hi = wi + qi * bq + bq - 1  # last query slot of this q block
    overlap = (kj * bk + bk > kv_start_ref[b]) & (kj * bk < kv_len_ref[b])
    live = overlap & (kj * bk <= q_hi)

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        k = k_ref[0, 0, 0]
        v = v_ref[0, 0, 0]
        # zero K/V rows outside the valid window BEFORE any matmul (cache
        # slots past the frontier may be uninitialized; 0 * NaN = NaN)
        cpos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bk, 1), 0)
        cok = (cpos >= kv_start_ref[b]) & (cpos < kv_len_ref[b])
        k = jnp.where(cok, k, 0)
        v = jnp.where(cok, v, 0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]

        q_pos = wi + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        ok = (k_pos >= kv_start_ref[b]) & (k_pos < kv_len_ref[b]) & (k_pos <= q_pos)
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(kj == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bq", "bk", "interpret"))
def chunk_prefill_attention(
    q: jax.Array,  # [B, S, H, hd] — one prompt chunk's fresh queries
    k_cache: jax.Array,  # [L, B, K, T, hd] — FULL stacked head-major cache
    v_cache: jax.Array,  # [L, B, K, T, hd]
    kv_start: jax.Array,  # [B] int32: first valid cache slot
    kv_len: jax.Array,  # [B] int32: valid frontier (= write_index + S)
    layer: jax.Array,  # [] or [1] int32
    write_index: jax.Array,  # [] or [1] int32: cache slot of query 0
    bq: int = 512,  # swept on v5e: ~5% over 256; wider is flat (per-head grid)
    bk: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Cache-wide flash attention for CHUNKED prefill (``S > 1`` queries
    written at ``write_index > 0``): each query attends over the whole
    populated cache prefix — earlier chunks' slots AND its own chunk — under
    offset causality (query ``t`` lives at cache slot ``write_index + t``).

    Streams the head-major cache exactly like ``decode_attention`` (layer via
    scalar prefetch into the block index map — no per-layer cache slice is
    materialized) but with the blockwise flash recurrence of
    ``flash_attention`` across ``bq`` query rows. The reference has no
    equivalent: its torch path materializes full [S, T] score matrices and
    cannot prefill beyond what fits one forward (rag.py:172)."""
    B, S, H, hd = q.shape
    L, _, K, T, _ = k_cache.shape
    G = H // K
    bq = _fit_block(S, bq)
    bk = _decode_block(T, bk)
    if not interpret and bk % 16:
        raise ValueError(
            f"cache length T={T} only tiles into blocks of {bk}: pad T to a "
            "multiple of 128 — the engine rounds cache lengths for this"
        )

    qt = q.transpose(0, 2, 1, 3).reshape(B * H, S, hd)
    grid = (B * H, S // bq, T // bk)

    def kv_index(bh, qi, kj, layer_ref, *s_):
        return (layer_ref[0], bh // H, (bh % H) // G, kj, 0)

    out = pl.pallas_call(
        functools.partial(
            _chunk_kernel, bq=bq, bk=bk, scale=hd**-0.5, num_heads=H
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, hd), lambda bh, qi, kj, *s_: (bh, qi, 0)),
                pl.BlockSpec((1, 1, 1, bk, hd), kv_index),
                pl.BlockSpec((1, 1, 1, bk, hd), kv_index),
            ],
            out_specs=pl.BlockSpec((1, bq, hd), lambda bh, qi, kj, *s_: (bh, qi, 0)),
            scratch_shapes=[
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, S, hd), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        jnp.asarray(write_index, jnp.int32).reshape(1),
        kv_start.astype(jnp.int32),
        kv_len.astype(jnp.int32),
        qt,
        k_cache,
        v_cache,
    )

    return out.reshape(B, H, S, hd).transpose(0, 2, 1, 3)


def chunk_attention_xla(
    q: jax.Array,  # [B, S, H, hd]
    k_cache: jax.Array,  # [L, B, K, T, hd]
    v_cache: jax.Array,  # [L, B, K, T, hd]
    kv_start: jax.Array,  # [B]
    kv_len: jax.Array,  # [B]
    layer: jax.Array,  # [] or [1] int32
    write_index: jax.Array,  # [] int32
) -> jax.Array:
    """Dense XLA reference for ``chunk_prefill_attention`` (oracle; fallback
    off-TPU)."""
    B, S, H, hd = q.shape
    _, _, K, T, _ = k_cache.shape
    G = H // K
    lay = jnp.asarray(layer, jnp.int32).reshape(())
    k = jax.lax.dynamic_index_in_dim(k_cache, lay, 0, keepdims=False)
    v = jax.lax.dynamic_index_in_dim(v_cache, lay, 0, keepdims=False)
    qg = q.reshape(B, S, K, G, hd)
    s = jnp.einsum("bqkgd,bktd->bkgqt", qg, k, preferred_element_type=jnp.float32)
    s = s * (hd**-0.5)
    q_pos = jnp.asarray(write_index, jnp.int32).reshape(()) + jnp.arange(S)
    t_pos = jnp.arange(T)
    ok = (t_pos[None, None, :] >= kv_start[:, None, None]) & (
        t_pos[None, None, :] < kv_len[:, None, None]
    )
    ok = ok & (t_pos[None, None, :] <= q_pos[None, :, None])  # [B, S, T]
    s = jnp.where(ok[:, None, None, :, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(ok[:, None, None, :, :], p, 0.0)
    o = jnp.einsum(
        "bkgqt,bktd->bqkgd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return o.reshape(B, S, H, hd).astype(q.dtype)


def decode_attention_xla(
    q: jax.Array,  # [B, 1, H, hd]
    k_cache: jax.Array,  # [L, B, K, T, hd]
    v_cache: jax.Array,  # [L, B, K, T, hd]
    kv_start: jax.Array,  # [B]
    kv_len: jax.Array,  # [B]
    layer: jax.Array,  # [] or [1] int32
) -> jax.Array:
    """Dense XLA reference for ``decode_attention`` (oracle; fallback off-TPU)."""
    B, S, H, hd = q.shape
    _, _, K, T, _ = k_cache.shape
    G = H // K
    lay = jnp.asarray(layer, jnp.int32).reshape(())
    k_cache = jax.lax.dynamic_index_in_dim(k_cache, lay, 0, keepdims=False)
    v_cache = jax.lax.dynamic_index_in_dim(v_cache, lay, 0, keepdims=False)
    qg = q.reshape(B, K, G, hd)
    s = jnp.einsum(
        "bkgd,bktd->bkgt", qg, k_cache, preferred_element_type=jnp.float32
    ) * (hd**-0.5)
    t_pos = jnp.arange(T)
    ok = (t_pos[None, :] >= kv_start[:, None]) & (t_pos[None, :] < kv_len[:, None])
    s = jnp.where(ok[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(ok[:, None, None, :], p, 0.0)
    o = jnp.einsum(
        "bkgt,bktd->bkgd", p.astype(v_cache.dtype), v_cache,
        preferred_element_type=jnp.float32,
    )
    return o.reshape(B, 1, H, hd).astype(q.dtype)


def attention_xla(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_start: Optional[jax.Array] = None,
    kv_len: Optional[jax.Array] = None,
    causal: bool = True,
) -> jax.Array:
    """Dense XLA reference (oracle for the kernel; fallback off-TPU)."""
    B, Sq, H, hd = q.shape
    _, Sk, K, _ = k.shape
    G = H // K
    qg = q.reshape(B, Sq, K, G, hd)
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32)
    s = s * (hd**-0.5)
    q_pos = jnp.arange(Sq)
    k_pos = jnp.arange(Sk)
    ok = jnp.ones((B, Sq, Sk), bool)
    if kv_start is not None:
        ok = ok & (k_pos[None, None, :] >= kv_start[:, None, None])
    if kv_len is not None:
        ok = ok & (k_pos[None, None, :] < kv_len[:, None, None])
    if causal:
        ok = ok & (k_pos[None, None, :] <= q_pos[None, :, None])
    s = jnp.where(ok[:, None, None, :, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # rows with no valid key: softmax of all-NEG_INF is uniform — zero it so
    # pad rows contribute nothing downstream (matches the fused kernels)
    p = jnp.where(ok[:, None, None, :, :], p, 0.0)
    o = jnp.einsum("bkgqs,bskd->bqkgd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o.reshape(B, Sq, H, hd).astype(q.dtype)


# ---------------------------------------------------------------------------
# RoPE re-rotation of cached K planes (chunk-granular prefix reuse)
# ---------------------------------------------------------------------------
#
# RoPE is a per-position orthogonal rotation of each (i, i + hd/2) pair of
# the K vector: K computed at position p and reused at position p + delta
# differs ONLY by a further rotation of angle delta * inv_freq per pair — a
# closed form over bytes already in HBM, no re-prefill (SIFT's attention
# invariance: retrieved-chunk KV is largely position/composition-invariant,
# so a hot chunk's KV is computed ONCE at a canonical position and spliced
# anywhere by rotating the cached K planes by the position delta). V carries
# no positional encoding and splices untouched. delta == 0 is exactly the
# identity (cos 0 = 1, sin 0 = 0 — the multiply-by-one round trip is exact
# in every dtype), so a canonical-position hit stays bit-identical.


@jax.jit
def rope_rerotate(k: jax.Array, delta: jax.Array, inv_freqs: jax.Array) -> jax.Array:
    """Rotate cached K planes ``[..., hd]`` by a uniform position ``delta``
    (scalar int): the pairwise-by-halves rotation of ``apply_rope`` with
    phase ``delta * inv_freq`` — position-shifting every token of a cached
    segment in one VPU pass. Computes in fp32, returns ``k``'s dtype."""
    half = k.shape[-1] // 2
    phase = delta.astype(jnp.float32) * inv_freqs  # [hd/2]
    c, s = jnp.cos(phase), jnp.sin(phase)
    x1 = k[..., :half].astype(jnp.float32)
    x2 = k[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
    ).astype(k.dtype)


@jax.jit
def rope_rerotate_q8(
    k_q: jax.Array,  # [..., hd] int8 payload
    k_scale: jax.Array,  # [...] fp32 per-(token, head) vector scale
    delta: jax.Array,
    inv_freqs: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """``rope_rerotate`` over the int8-quantized K layout (the warm tier /
    int8-KV engines): dequant → rotate → requant. The rotation pairs dims
    ``i`` and ``i + hd/2`` of the SAME token vector, which shares one
    symmetric scale — but it changes the vector's max-abs, so the scale is
    recomputed per vector (same grammar as :func:`quantize_kv`) instead of
    carried; drift stays bounded at max|x|/254 per element either way."""
    xf = k_q.astype(jnp.float32) * k_scale[..., None]
    half = xf.shape[-1] // 2
    phase = delta.astype(jnp.float32) * inv_freqs
    c, s = jnp.cos(phase), jnp.sin(phase)
    x1, x2 = xf[..., :half], xf[..., half:]
    rot = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    scale = jnp.maximum(jnp.max(jnp.abs(rot), axis=-1), 1e-8) / 127.0
    q = jnp.round(rot / scale[..., None]).astype(jnp.int8)
    return q, scale


# ---------------------------------------------------------------------------
# weight-only-int8 KV cache (kv_quant="int8")
# ---------------------------------------------------------------------------
#
# At the engine's full cache budget the decode step's HBM traffic is weights
# PLUS the whole populated cache (e.g. 8B, B=8, T=4352: ~8 GiB int8 weights
# + ~4.6 GB bf16 cache per step). Storing K/V as int8 with one fp32 scale
# per (token, kv-head) vector halves the cache bytes streamed and the cache
# HBM footprint; dequantization happens in VMEM right after each block load,
# so the flash recurrence and masking below are IDENTICAL to the bf16
# kernel's. Per-vector symmetric scales bound the dequant error at
# max|x|/254 per element — the parity tests pin logits against the bf16
# cache path.


def quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """``[..., hd] -> (int8 [..., hd], fp32 scale [...])`` — one symmetric
    scale per head-vector (the granularity the kernels dequantize at)."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    q = jnp.round(xf / scale[..., None]).astype(jnp.int8)
    return q, scale


def dequantize_layer_slice(
    cache: jax.Array,  # [L, B, K, T, hd] int8
    scale: jax.Array,  # [L, B, K, T] fp32
    layer: jax.Array,  # [] or [1] int32
    kv_start: jax.Array,  # [B]
    kv_len: jax.Array,  # [B]
    dtype: jnp.dtype,
) -> jax.Array:
    """``[1, B, K, T, hd]`` dequantized view of ONE layer — the shared
    slice-dequant used by the XLA q8 oracle and the chunked-prefill path
    (a layer slice is ~MBs; the stacked cache the q8 layout exists to avoid
    copying is GBs). Scales outside ``[kv_start, kv_len)`` zero out under
    the window mask: slots past the frontier can be uninitialized fp32
    memory (NaN), while the int8 payload is finite by construction, so
    zeroed scales alone make every invalid slot contribute exactly 0."""
    lay = jnp.asarray(layer, jnp.int32).reshape(())
    T = cache.shape[3]
    t_ok = (jnp.arange(T)[None, :] >= kv_start[:, None]) & (
        jnp.arange(T)[None, :] < kv_len[:, None]
    )
    c = jax.lax.dynamic_index_in_dim(cache, lay, 0, keepdims=False)
    s = jax.lax.dynamic_index_in_dim(scale, lay, 0, keepdims=False)
    s = jnp.where(t_ok[:, None, :], s, 0.0)
    return (c.astype(jnp.float32) * s[..., None]).astype(dtype)[None]


def _decode_kernel_q8(
    layer_ref,  # SMEM [1] (consumed by the index maps)
    kv_start_ref,  # SMEM [B]
    kv_len_ref,  # SMEM [B]
    q_ref,  # [1, K, G, hd]
    k_ref,  # [1, 1, K, bk, hd] int8
    v_ref,  # [1, 1, K, bk, hd] int8
    ks_ref,  # [1, 1, K, bk] fp32
    vs_ref,  # [1, 1, K, bk] fp32
    o_ref,  # [1, K, G, hd]
    m_scr,  # VMEM [K, G, 1]
    l_scr,  # VMEM [K, G, 1]
    acc_scr,  # VMEM [K, G, hd]
    *,
    bk: int,
    scale: float,
):
    b = pl.program_id(0)
    kj = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    blk_lo = kj * bk
    live = (blk_lo < kv_len_ref[b]) & (blk_lo + bk > kv_start_ref[b])

    @pl.when(live)
    def _compute():
        q = q_ref[0]  # [K, G, hd]
        # int8 payloads need NO validity masking: unlike bf16 (where an
        # uninitialized slot can hold NaN that survives 0-weighting), every
        # int8 bit pattern is a finite value, and invalid columns are
        # eliminated by the score mask + zeroed scales below. The convert
        # to the matmul dtype is the only per-element op on the payload.
        k = k_ref[0, 0].astype(q.dtype)  # [K, bk, hd]
        rpos = blk_lo + jax.lax.broadcasted_iota(
            jnp.int32, (k.shape[0], bk), 1
        )
        rok = (rpos >= kv_start_ref[b]) & (rpos < kv_len_ref[b])
        # scales CAN be NaN past the frontier (uninitialized fp32 memory):
        # zero them under the window mask — [K, bk] work, not [K, bk, hd]
        ks = jnp.where(rok, ks_ref[0, 0], 0.0)
        vs = jnp.where(rok, vs_ref[0, 0], 0.0)
        # dequantization rides the EPILOGUES: scores scale per key column,
        # probabilities fold the V scale — O(K*G*bk) multiplies instead of
        # O(K*bk*hd) on the payload (the whole point: the int8 win is
        # bandwidth, so the kernel must not spend it back in VPU flops)
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * scale * ks[:, None, :]

        k_pos = blk_lo + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        ok = (k_pos >= kv_start_ref[b]) & (k_pos < kv_len_ref[b])
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=2, keepdims=True)
        pv = (p * vs[:, None, :]).astype(q.dtype)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pv, v_ref[0, 0].astype(q.dtype), (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(kj == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bk", "interpret"))
def decode_attention_q8(
    q: jax.Array,  # [B, 1, H, hd] — the single fresh query token
    k_cache: jax.Array,  # [L, B, K, T, hd] int8
    v_cache: jax.Array,  # [L, B, K, T, hd] int8
    k_scale: jax.Array,  # [L, B, K, T] fp32
    v_scale: jax.Array,  # [L, B, K, T] fp32
    kv_start: jax.Array,  # [B] int32
    kv_len: jax.Array,  # [B] int32
    layer: jax.Array,  # [] or [1] int32
    bk: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """``decode_attention`` over an int8 KV cache (see module note above).

    Same grid, masking, and streaming layout as the bf16 kernel; the only
    addition is the two per-(token, head) scale planes riding alongside the
    int8 payload blocks."""
    B, S, H, hd = q.shape
    assert S == 1, f"decode_attention_q8 is single-token (got S={S})"
    L, _, K, T, _ = k_cache.shape
    G = H // K
    req_bk = bk
    bk = _decode_block(T, bk)
    assert T % bk == 0, (T, bk)
    if not interpret and bk % 32:
        # int8 blocks need a 32-row second-to-minor tile on real hardware
        raise ValueError(
            f"cache length T={T} only tiles into blocks of {bk} ≤ bk={req_bk}: "
            "pad T to a multiple of 128 — the engine rounds cache lengths for this"
        )

    qh = q.reshape(B, K, G, hd)
    grid = (B, T // bk)

    def kv_index(b, kj, layer_ref, *s_):
        return (layer_ref[0], b, 0, kj, 0)

    def sc_index(b, kj, layer_ref, *s_):
        return (layer_ref[0], b, 0, kj)

    out = pl.pallas_call(
        functools.partial(_decode_kernel_q8, bk=bk, scale=hd**-0.5),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, K, G, hd), lambda b, kj, *s_: (b, 0, 0, 0)),
                pl.BlockSpec((1, 1, K, bk, hd), kv_index),
                pl.BlockSpec((1, 1, K, bk, hd), kv_index),
                pl.BlockSpec((1, 1, K, bk), sc_index),
                pl.BlockSpec((1, 1, K, bk), sc_index),
            ],
            out_specs=pl.BlockSpec((1, K, G, hd), lambda b, kj, *s_: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((K, G, 1), jnp.float32),
                pltpu.VMEM((K, G, 1), jnp.float32),
                pltpu.VMEM((K, G, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, K, G, hd), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        kv_start.astype(jnp.int32),
        kv_len.astype(jnp.int32),
        qh,
        k_cache,
        v_cache,
        k_scale,
        v_scale,
    )

    return out.reshape(B, 1, H, hd)


def _chunk_kernel_q8(
    layer_ref,  # SMEM [1] (consumed by the index maps)
    wi_ref,  # SMEM [1]: write_index — global cache slot of query 0
    kv_start_ref,  # SMEM [B]
    kv_len_ref,  # SMEM [B]
    q_ref,  # [1, bq, hd]
    k_ref,  # [1, 1, 1, bk, hd] int8
    v_ref,  # [1, 1, 1, bk, hd] int8
    ks_ref,  # [1, 1, K, bk] fp32 — ALL kv heads' scales for this block range
    vs_ref,  # [1, 1, K, bk] fp32
    o_ref,  # [1, bq, hd]
    m_scr,  # VMEM [bq, 1]
    l_scr,  # VMEM [bq, 1]
    acc_scr,  # VMEM [bq, hd]
    *,
    bq: int,
    bk: int,
    scale: float,
    num_heads: int,
    group: int,
):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    b = bh // num_heads
    # Mosaic's tile rules reject a (1, bk) scale block ((1, 1, 1, bk) spec:
    # second-to-minor 1 neither divides 8 nor equals K), so the block carries
    # all K heads' scales — KBs — and the kernel row-selects its own kv head
    # with an iota mask (a [K, bk] VPU reduce, nothing on the payload path)
    kvh = (bh % num_heads) // group
    wi = wi_ref[0]

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_hi = wi + qi * bq + bq - 1  # last query slot of this q block
    overlap = (kj * bk + bk > kv_start_ref[b]) & (kj * bk < kv_len_ref[b])
    live = overlap & (kj * bk <= q_hi)

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        # int8 payloads need NO validity masking (every bit pattern is
        # finite); invalid columns die via the score mask + zeroed scales —
        # dequantization rides the epilogues exactly as in _decode_kernel_q8
        k = k_ref[0, 0, 0].astype(q.dtype)  # [bk, hd]
        rows = jax.lax.broadcasted_iota(jnp.int32, ks_ref.shape[2:], 0)  # [K, bk]
        ks_row = jnp.sum(jnp.where(rows == kvh, ks_ref[0, 0], 0.0), axis=0)
        vs_row = jnp.sum(jnp.where(rows == kvh, vs_ref[0, 0], 0.0), axis=0)
        cpos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        cok = (cpos >= kv_start_ref[b]) & (cpos < kv_len_ref[b])
        # scales CAN be NaN past the frontier (uninitialized fp32 memory)
        ks = jnp.where(cok, ks_row[None, :], 0.0)  # [1, bk]
        vs = jnp.where(cok, vs_row[None, :], 0.0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale * ks  # [bq, bk]; ks broadcasts over the bq rows

        q_pos = wi + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        ok = (k_pos >= kv_start_ref[b]) & (k_pos < kv_len_ref[b]) & (k_pos <= q_pos)
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = (p * vs).astype(q.dtype)  # V scale folded into the prob matrix
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pv, v_ref[0, 0, 0].astype(q.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(kj == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bq", "bk", "interpret"))
def chunk_prefill_attention_q8(
    q: jax.Array,  # [B, S, H, hd] — one prompt chunk's fresh queries
    k_cache: jax.Array,  # [L, B, K, T, hd] int8
    v_cache: jax.Array,  # [L, B, K, T, hd] int8
    k_scale: jax.Array,  # [L, B, K, T] fp32
    v_scale: jax.Array,  # [L, B, K, T] fp32
    kv_start: jax.Array,  # [B] int32
    kv_len: jax.Array,  # [B] int32
    layer: jax.Array,  # [] or [1] int32
    write_index: jax.Array,  # [] or [1] int32: cache slot of query 0
    bq: int = 512,
    bk: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """``chunk_prefill_attention`` over an int8 KV cache: offset-causal
    flash attention where each query block streams the int8 cache blocks
    directly and dequantizes in the matmul EPILOGUES (score × k-scale,
    prob × v-scale) — the long-prompt int8 path never materializes a bf16
    layer slice, so chunked prefill keeps the bandwidth int8 bought.
    (Round 3 dequantized ``[1, B, K, T, hd]`` bf16 per layer per chunk.)"""
    B, S, H, hd = q.shape
    L, _, K, T, _ = k_cache.shape
    G = H // K
    bq = _fit_block(S, bq)
    bk = _decode_block(T, bk)
    if not interpret and bk % 32:
        # int8 blocks need a 32-row second-to-minor tile on real hardware
        raise ValueError(
            f"cache length T={T} only tiles into blocks of {bk}: pad T to a "
            "multiple of 128 — the engine rounds cache lengths for this"
        )

    qt = q.transpose(0, 2, 1, 3).reshape(B * H, S, hd)
    grid = (B * H, S // bq, T // bk)

    def kv_index(bh, qi, kj, layer_ref, *s_):
        return (layer_ref[0], bh // H, (bh % H) // G, kj, 0)

    def sc_index(bh, qi, kj, layer_ref, *s_):
        return (layer_ref[0], bh // H, 0, kj)

    out = pl.pallas_call(
        functools.partial(
            _chunk_kernel_q8, bq=bq, bk=bk, scale=hd**-0.5, num_heads=H, group=G
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, hd), lambda bh, qi, kj, *s_: (bh, qi, 0)),
                pl.BlockSpec((1, 1, 1, bk, hd), kv_index),
                pl.BlockSpec((1, 1, 1, bk, hd), kv_index),
                pl.BlockSpec((1, 1, K, bk), sc_index),
                pl.BlockSpec((1, 1, K, bk), sc_index),
            ],
            out_specs=pl.BlockSpec((1, bq, hd), lambda bh, qi, kj, *s_: (bh, qi, 0)),
            scratch_shapes=[
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, S, hd), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        jnp.asarray(write_index, jnp.int32).reshape(1),
        kv_start.astype(jnp.int32),
        kv_len.astype(jnp.int32),
        qt,
        k_cache,
        v_cache,
        k_scale,
        v_scale,
    )

    return out.reshape(B, H, S, hd).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# paged KV cache (block-pool arena + per-row block tables)
# ---------------------------------------------------------------------------
#
# The dense decode kernels above stream a [L, B, K, T, hd] cache whose T is
# the engine's FULL window for every row — at B=64 that is mostly pad (a
# 300-token prompt in a 4352-slot row), and the bandwidth-bound decode step
# pays for every byte of it. The paged layout replaces the per-row T axis
# with a POOL of fixed-size blocks, [L, N, K, bs, hd], plus a per-row int32
# block table mapping logical block j of row b to a physical pool block.
# The kernels below are the dense kernels with ONE change: the K/V block
# index map reads the table (scalar prefetch, SMEM) instead of computing
# kj directly — the flash recurrence, masking, and out-of-window block skip
# are identical, and only a row's LIVE blocks are ever streamed, so decode
# bandwidth scales with real tokens, not the window.
#
# Geometry: paged rows are RIGHT-padded — logical positions start at 0, the
# valid window is [0, kv_len), and kv_start does not exist (this is also
# what makes prefix blocks shareable: a shared prompt head always occupies
# logical blocks 0..n at identical in-block offsets). Table entries for
# blocks a row has not reached point at the reserved null block 0
# (engine/kv_pool.py): the index map may prefetch it, but the block-skip
# predicate (kj * bs >= kv_len) guarantees it is never computed on.
#
# Tensor parallelism: on a tp>1 mesh the arena is HEAD-SHARDED — each device
# holds [L, N, K/tp, bs, hd], i.e. its K/tp kv heads of EVERY physical block
# (paged_partition_specs below; models/llama.py wraps these kernels in
# shard_map with exactly those rules). Block tables, kv_len, and the layer
# scalar stay replicated: allocation is per-ROW, never per-head, so one
# host-side table drives all shards and the free-list/ref-count allocator
# needs no tp awareness at all. Inside the shard each kernel is UNCHANGED —
# K in the shapes above is simply the local head count — and per-device
# decode bandwidth scales as live_tokens × K/tp; the cross-device reduce is
# the wo projection's row-parallel psum that XLA already inserts, identical
# to the dense tp path.


def _paged_decode_kernel(
    layer_ref,  # SMEM [1] (consumed by the index maps)
    tables_ref,  # SMEM [B * MB]: flattened block tables (index maps)
    kv_len_ref,  # SMEM [B]: valid logical frontier (exclusive)
    q_ref,  # [1, K, G, hd]
    k_ref,  # [1, 1, K, bs, hd] — the PHYSICAL block the table named
    v_ref,  # [1, 1, K, bs, hd]
    o_ref,  # [1, K, G, hd]
    m_scr,  # VMEM [K, G, 1]
    l_scr,  # VMEM [K, G, 1]
    acc_scr,  # VMEM [K, G, hd]
    *,
    bs: int,
    scale: float,
):
    b = pl.program_id(0)
    kj = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # logical block skip: blocks at/after the frontier were never allocated
    # (their table entries are the null block) — no work, no reads counted
    blk_lo = kj * bs
    live = blk_lo < kv_len_ref[b]

    @pl.when(live)
    def _compute():
        q = q_ref[0]  # [K, G, hd]
        k = k_ref[0, 0]  # [K, bs, hd]
        v = v_ref[0, 0]
        # zero K/V rows past the frontier BEFORE any matmul: the frontier
        # block's tail slots may be uninitialized device memory, and a NaN
        # there survives even a zero-weight product (0 * NaN = NaN)
        rpos = blk_lo + jax.lax.broadcasted_iota(
            jnp.int32, (k.shape[0], k.shape[1], 1), 1
        )
        rok = rpos < kv_len_ref[b]
        k = jnp.where(rok, k, 0)
        v = jnp.where(rok, v, 0)
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * scale  # [K, G, bs]

        k_pos = blk_lo + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        ok = k_pos < kv_len_ref[b]
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=2, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(kj == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention(
    q: jax.Array,  # [B, 1, H, hd] — the single fresh query token
    k_arena: jax.Array,  # [L, N, K, bs, hd] — the block-pool arena
    v_arena: jax.Array,  # [L, N, K, bs, hd]
    block_tables: jax.Array,  # [B, MB] int32: logical block -> physical block
    kv_len: jax.Array,  # [B] int32: valid logical frontier (exclusive)
    layer: jax.Array,  # [] or [1] int32
    interpret: bool = False,
) -> jax.Array:
    """``decode_attention`` over a paged arena: one grid cell per (row,
    logical block), the physical block resolved by the row's table inside
    the block index map (scalar prefetch — the table never leaves SMEM).
    Streaming layout, flash recurrence, and masking match the dense kernel;
    the only difference is WHICH ``(bs, hd)`` slabs get DMA'd."""
    B, S, H, hd = q.shape
    assert S == 1, f"paged_decode_attention is single-token (got S={S})"
    L, N, K, bs, _ = k_arena.shape
    G = H // K
    MB = block_tables.shape[1]
    if not interpret and bs % 16:
        raise ValueError(
            f"paged block_size={bs} must be a multiple of the Mosaic 16-row "
            "bf16 tile (EngineConfig.kv_block_size)"
        )

    qh = q.reshape(B, K, G, hd)
    grid = (B, MB)

    def kv_index(b, kj, layer_ref, tables_ref, *s_):
        return (layer_ref[0], tables_ref[b * MB + kj], 0, 0, 0)

    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, bs=bs, scale=hd**-0.5),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, K, G, hd), lambda b, kj, *s_: (b, 0, 0, 0)),
                pl.BlockSpec((1, 1, K, bs, hd), kv_index),
                pl.BlockSpec((1, 1, K, bs, hd), kv_index),
            ],
            out_specs=pl.BlockSpec((1, K, G, hd), lambda b, kj, *s_: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((K, G, 1), jnp.float32),
                pltpu.VMEM((K, G, 1), jnp.float32),
                pltpu.VMEM((K, G, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, K, G, hd), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        block_tables.astype(jnp.int32).reshape(-1),
        kv_len.astype(jnp.int32),
        qh,
        k_arena,
        v_arena,
    )

    return out.reshape(B, 1, H, hd)


def _paged_decode_kernel_q8(
    layer_ref,  # SMEM [1]
    tables_ref,  # SMEM [B * MB]
    kv_len_ref,  # SMEM [B]
    q_ref,  # [1, K, G, hd]
    k_ref,  # [1, 1, K, bs, hd] int8
    v_ref,  # [1, 1, K, bs, hd] int8
    ks_ref,  # [1, 1, K, bs] fp32
    vs_ref,  # [1, 1, K, bs] fp32
    o_ref,  # [1, K, G, hd]
    m_scr,  # VMEM [K, G, 1]
    l_scr,  # VMEM [K, G, 1]
    acc_scr,  # VMEM [K, G, hd]
    *,
    bs: int,
    scale: float,
):
    b = pl.program_id(0)
    kj = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    blk_lo = kj * bs
    live = blk_lo < kv_len_ref[b]

    @pl.when(live)
    def _compute():
        q = q_ref[0]  # [K, G, hd]
        # int8 payloads need NO validity masking (every bit pattern is
        # finite); invalid columns die via the score mask + zeroed scales,
        # dequantization rides the epilogues exactly as in _decode_kernel_q8
        k = k_ref[0, 0].astype(q.dtype)  # [K, bs, hd]
        rpos = blk_lo + jax.lax.broadcasted_iota(jnp.int32, (k.shape[0], bs), 1)
        rok = rpos < kv_len_ref[b]
        # scales CAN be NaN past the frontier (uninitialized fp32 memory)
        ks = jnp.where(rok, ks_ref[0, 0], 0.0)
        vs = jnp.where(rok, vs_ref[0, 0], 0.0)
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * scale * ks[:, None, :]

        k_pos = blk_lo + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        ok = k_pos < kv_len_ref[b]
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=2, keepdims=True)
        pv = (p * vs[:, None, :]).astype(q.dtype)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pv, v_ref[0, 0].astype(q.dtype), (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(kj == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_q8(
    q: jax.Array,  # [B, 1, H, hd]
    k_arena: jax.Array,  # [L, N, K, bs, hd] int8
    v_arena: jax.Array,  # [L, N, K, bs, hd] int8
    k_scale: jax.Array,  # [L, N, K, bs] fp32
    v_scale: jax.Array,  # [L, N, K, bs] fp32
    block_tables: jax.Array,  # [B, MB] int32
    kv_len: jax.Array,  # [B] int32
    layer: jax.Array,  # [] or [1] int32
    interpret: bool = False,
) -> jax.Array:
    """``paged_decode_attention`` over an int8 arena: the table indirection
    of the paged kernel + the epilogue dequantization of the q8 kernel."""
    B, S, H, hd = q.shape
    assert S == 1, f"paged_decode_attention_q8 is single-token (got S={S})"
    L, N, K, bs, _ = k_arena.shape
    G = H // K
    MB = block_tables.shape[1]
    if not interpret and bs % 32:
        # int8 blocks need a 32-row second-to-minor tile on real hardware
        raise ValueError(
            f"paged block_size={bs} must be a multiple of the Mosaic 32-row "
            "int8 tile under kv_quant='int8' (EngineConfig.kv_block_size)"
        )

    qh = q.reshape(B, K, G, hd)
    grid = (B, MB)

    def kv_index(b, kj, layer_ref, tables_ref, *s_):
        return (layer_ref[0], tables_ref[b * MB + kj], 0, 0, 0)

    def sc_index(b, kj, layer_ref, tables_ref, *s_):
        return (layer_ref[0], tables_ref[b * MB + kj], 0, 0)

    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel_q8, bs=bs, scale=hd**-0.5),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, K, G, hd), lambda b, kj, *s_: (b, 0, 0, 0)),
                pl.BlockSpec((1, 1, K, bs, hd), kv_index),
                pl.BlockSpec((1, 1, K, bs, hd), kv_index),
                pl.BlockSpec((1, 1, K, bs), sc_index),
                pl.BlockSpec((1, 1, K, bs), sc_index),
            ],
            out_specs=pl.BlockSpec((1, K, G, hd), lambda b, kj, *s_: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((K, G, 1), jnp.float32),
                pltpu.VMEM((K, G, 1), jnp.float32),
                pltpu.VMEM((K, G, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, K, G, hd), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        block_tables.astype(jnp.int32).reshape(-1),
        kv_len.astype(jnp.int32),
        qh,
        k_arena,
        v_arena,
        k_scale,
        v_scale,
    )

    return out.reshape(B, 1, H, hd)


def _paged_chunk_kernel(
    layer_ref,  # SMEM [1]
    wi_ref,  # SMEM [B]: per-row logical slot of query 0
    tables_ref,  # SMEM [B * MB]
    kv_len_ref,  # SMEM [B]
    q_ref,  # [1, bq, hd]
    k_ref,  # [1, 1, 1, bs, hd]
    v_ref,  # [1, 1, 1, bs, hd]
    o_ref,  # [1, bq, hd]
    m_scr,  # VMEM [bq, 1]
    l_scr,  # VMEM [bq, 1]
    acc_scr,  # VMEM [bq, hd]
    *,
    bq: int,
    bs: int,
    scale: float,
    num_heads: int,
):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    b = bh // num_heads
    wi = wi_ref[b]

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # block skip: logical blocks past the frontier or strictly above the
    # OFFSET causal diagonal (query t sits at logical slot wi + t) do no work
    q_hi = wi + qi * bq + bq - 1
    live = (kj * bs < kv_len_ref[b]) & (kj * bs <= q_hi)

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        k = k_ref[0, 0, 0]
        v = v_ref[0, 0, 0]
        # zero K/V rows past the frontier BEFORE any matmul (frontier-block
        # tail slots may be uninitialized; 0 * NaN = NaN)
        cpos = kj * bs + jax.lax.broadcasted_iota(jnp.int32, (bs, 1), 0)
        cok = cpos < kv_len_ref[b]
        k = jnp.where(cok, k, 0)
        v = jnp.where(cok, v, 0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bs]

        q_pos = wi + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bs), 0)
        k_pos = kj * bs + jax.lax.broadcasted_iota(jnp.int32, (bq, bs), 1)
        ok = (k_pos < kv_len_ref[b]) & (k_pos <= q_pos)
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(kj == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bq", "interpret"))
def paged_chunk_attention(
    q: jax.Array,  # [B, S, H, hd] — one prompt chunk's fresh queries
    k_arena: jax.Array,  # [L, N, K, bs, hd]
    v_arena: jax.Array,  # [L, N, K, bs, hd]
    block_tables: jax.Array,  # [B, MB] int32
    kv_len: jax.Array,  # [B] int32: valid frontier (= write_index + chunk len)
    layer: jax.Array,  # [] or [1] int32
    write_index: jax.Array,  # [B] int32: per-row logical slot of query 0
    bq: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """``chunk_prefill_attention`` over a paged arena (the paged
    chunked-prefill path): each query block streams its row's LIVE blocks
    via the table with offset causality. The chunk's own K/V must already
    be scattered into the row's blocks (the model writes before attending,
    exactly like the dense chunk path). ``write_index`` is per-row — paged
    rows are right-padded, so rows at different depths chunk together.

    This is also THE multi-position paged DECODE kernel: the speculative
    verify step (``ContinuousEngine._build_verify_paged``) feeds every
    row ``last_tok`` + its K drafted tokens as one S = K+1 "chunk" at the
    row's own frontier (``write_index = kv_len``, per-row), so a verify
    window streams each row's live blocks ONCE for K+1 query lanes —
    decode is bandwidth-bound, which is exactly why a K+1-wide verify
    costs ~one decode step. Junk lanes past a row's real draft count are
    masked by its ``kv_len`` window, never by extra kernel logic.

    Its third consumer is the UNIFIED ragged sync window
    (``ContinuousEngine._build_mixed_step``, ISSUE 16): decode lanes
    (write_index = the row's frontier, one real query) and
    chunked-prefill lanes (write_index = the admission's progress
    offset, up to S real queries) ride the SAME S-wide call — the
    per-row ``write_index``/``kv_len`` vectors are what lets rows play
    different roles in one grid, with no new kernel logic."""
    B, S, H, hd = q.shape
    L, N, K, bs, _ = k_arena.shape
    G = H // K
    MB = block_tables.shape[1]
    bq = _fit_block(S, bq)
    if not interpret and bs % 16:
        raise ValueError(
            f"paged block_size={bs} must be a multiple of the Mosaic 16-row "
            "bf16 tile (EngineConfig.kv_block_size)"
        )

    qt = q.transpose(0, 2, 1, 3).reshape(B * H, S, hd)
    grid = (B * H, S // bq, MB)

    def kv_index(bh, qi, kj, layer_ref, wi_ref, tables_ref, *s_):
        return (
            layer_ref[0],
            tables_ref[(bh // H) * MB + kj],
            (bh % H) // G,
            0,
            0,
        )

    out = pl.pallas_call(
        functools.partial(
            _paged_chunk_kernel, bq=bq, bs=bs, scale=hd**-0.5, num_heads=H
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, hd), lambda bh, qi, kj, *s_: (bh, qi, 0)),
                pl.BlockSpec((1, 1, 1, bs, hd), kv_index),
                pl.BlockSpec((1, 1, 1, bs, hd), kv_index),
            ],
            out_specs=pl.BlockSpec((1, bq, hd), lambda bh, qi, kj, *s_: (bh, qi, 0)),
            scratch_shapes=[
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, S, hd), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        jnp.broadcast_to(jnp.asarray(write_index, jnp.int32), (B,)),
        block_tables.astype(jnp.int32).reshape(-1),
        kv_len.astype(jnp.int32),
        qt,
        k_arena,
        v_arena,
    )

    return out.reshape(B, H, S, hd).transpose(0, 2, 1, 3)


def _paged_chunk_kernel_q8(
    layer_ref,  # SMEM [1]
    wi_ref,  # SMEM [B]: per-row logical slot of query 0
    tables_ref,  # SMEM [B * MB]
    kv_len_ref,  # SMEM [B]
    q_ref,  # [1, bq, hd]
    k_ref,  # [1, 1, 1, bs, hd] int8
    v_ref,  # [1, 1, 1, bs, hd] int8
    ks_ref,  # [1, 1, K, bs] fp32 — ALL kv heads' scales for this block
    vs_ref,  # [1, 1, K, bs] fp32
    o_ref,  # [1, bq, hd]
    m_scr,  # VMEM [bq, 1]
    l_scr,  # VMEM [bq, 1]
    acc_scr,  # VMEM [bq, hd]
    *,
    bq: int,
    bs: int,
    scale: float,
    num_heads: int,
    group: int,
):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    b = bh // num_heads
    # same Mosaic tile workaround as _chunk_kernel_q8: a (1, bs) scale
    # block is untileable, so the block carries all K heads' scales and
    # the kernel row-selects its own kv head with an iota mask
    kvh = (bh % num_heads) // group
    wi = wi_ref[b]

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # block skip: logical blocks past the frontier or strictly above the
    # OFFSET causal diagonal (query t sits at logical slot wi + t) do no work
    q_hi = wi + qi * bq + bq - 1
    live = (kj * bs < kv_len_ref[b]) & (kj * bs <= q_hi)

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        # int8 payloads need NO validity masking (every bit pattern is
        # finite); invalid columns die via the score mask + zeroed scales —
        # dequantization rides the matmul EPILOGUES (score × k-scale,
        # prob × v-scale) exactly as in the dense q8 chunk kernel, so
        # warm-tier prefill keeps the bandwidth int8 bought instead of
        # paying the gather oracle's
        k = k_ref[0, 0, 0].astype(q.dtype)  # [bs, hd]
        rows = jax.lax.broadcasted_iota(jnp.int32, ks_ref.shape[2:], 0)  # [K, bs]
        ks_row = jnp.sum(jnp.where(rows == kvh, ks_ref[0, 0], 0.0), axis=0)
        vs_row = jnp.sum(jnp.where(rows == kvh, vs_ref[0, 0], 0.0), axis=0)
        cpos = kj * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        cok = cpos < kv_len_ref[b]
        # scales CAN be NaN past the frontier (uninitialized fp32 memory)
        ks = jnp.where(cok, ks_row[None, :], 0.0)  # [1, bs]
        vs = jnp.where(cok, vs_row[None, :], 0.0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale * ks  # [bq, bs]; ks broadcasts over the bq rows

        q_pos = wi + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bs), 0)
        k_pos = kj * bs + jax.lax.broadcasted_iota(jnp.int32, (bq, bs), 1)
        ok = (k_pos < kv_len_ref[b]) & (k_pos <= q_pos)
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = (p * vs).astype(q.dtype)  # V scale folded into the prob matrix
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pv, v_ref[0, 0, 0].astype(q.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(kj == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bq", "interpret"))
def paged_chunk_attention_q8(
    q: jax.Array,  # [B, S, H, hd] — one prompt chunk's fresh queries
    k_arena: jax.Array,  # [L, N, K, bs, hd] int8
    v_arena: jax.Array,  # [L, N, K, bs, hd] int8
    k_scale: jax.Array,  # [L, N, K, bs] fp32
    v_scale: jax.Array,  # [L, N, K, bs] fp32
    block_tables: jax.Array,  # [B, MB] int32
    kv_len: jax.Array,  # [B] int32
    layer: jax.Array,  # [] or [1] int32
    write_index: jax.Array,  # [B] int32: per-row logical slot of query 0
    bq: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """``paged_chunk_attention`` over an int8 arena: the table indirection
    of the paged chunk kernel + the epilogue dequantization of the q8
    kernels. PR 5 left this path on the gather XLA oracle — which
    materialized a dequantized logical view per layer, spending the
    bandwidth the int8 arena bought; fused, warm-tier (int8) chunked
    prefill streams the int8 blocks directly like every other q8 path."""
    B, S, H, hd = q.shape
    L, N, K, bs, _ = k_arena.shape
    G = H // K
    MB = block_tables.shape[1]
    bq = _fit_block(S, bq)
    if not interpret and bs % 32:
        # int8 blocks need a 32-row second-to-minor tile on real hardware
        raise ValueError(
            f"paged block_size={bs} must be a multiple of the Mosaic 32-row "
            "int8 tile under kv_quant='int8' (EngineConfig.kv_block_size)"
        )

    qt = q.transpose(0, 2, 1, 3).reshape(B * H, S, hd)
    grid = (B * H, S // bq, MB)

    def kv_index(bh, qi, kj, layer_ref, wi_ref, tables_ref, *s_):
        return (
            layer_ref[0],
            tables_ref[(bh // H) * MB + kj],
            (bh % H) // G,
            0,
            0,
        )

    def sc_index(bh, qi, kj, layer_ref, wi_ref, tables_ref, *s_):
        return (layer_ref[0], tables_ref[(bh // H) * MB + kj], 0, 0)

    out = pl.pallas_call(
        functools.partial(
            _paged_chunk_kernel_q8, bq=bq, bs=bs, scale=hd**-0.5,
            num_heads=H, group=G,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, hd), lambda bh, qi, kj, *s_: (bh, qi, 0)),
                pl.BlockSpec((1, 1, 1, bs, hd), kv_index),
                pl.BlockSpec((1, 1, 1, bs, hd), kv_index),
                pl.BlockSpec((1, 1, K, bs), sc_index),
                pl.BlockSpec((1, 1, K, bs), sc_index),
            ],
            out_specs=pl.BlockSpec((1, bq, hd), lambda bh, qi, kj, *s_: (bh, qi, 0)),
            scratch_shapes=[
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, S, hd), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        jnp.broadcast_to(jnp.asarray(write_index, jnp.int32), (B,)),
        block_tables.astype(jnp.int32).reshape(-1),
        kv_len.astype(jnp.int32),
        qt,
        k_arena,
        v_arena,
        k_scale,
        v_scale,
    )

    return out.reshape(B, H, S, hd).transpose(0, 2, 1, 3)


def paged_partition_specs(mode: str, q8: bool = False):
    """``(in_specs, out_spec)`` for ``shard_map``-ing the paged kernels over
    the ``tp`` mesh axis — THE partition rules of the head-sharded arena
    layout (kept here, next to the kernels they describe, so the model and
    the parity tests lower the exact same specs):

    - q / output ``[B, S, H, hd]`` → heads over ``tp``;
    - arena planes ``[L, N, K, bs, hd]`` (and ``[L, N, K, bs]`` scales) →
      kv heads over ``tp``: every device holds K/tp heads of EVERY block;
    - block tables ``[B, MB]``, ``kv_len [B]``, ``layer [1]``, and the
      chunk path's per-row ``write_index [B]`` → replicated (allocation is
      per-row, so one host table serves all shards).

    ``mode``: ``"decode"`` (args ``q, k, v[, ks, vs], tables, kv_len,
    layer``) or ``"chunk"`` (args ``q, k, v[, ks, vs], tables, kv_len,
    layer, wi``)."""
    from jax.sharding import PartitionSpec as P

    hspec = P(None, None, "tp", None)  # q / o: [B, S, H, hd]
    aspec = P(None, None, "tp", None, None)  # arena: [L, N, K, bs, hd]
    sspec = P(None, None, "tp", None)  # scales: [L, N, K, bs]
    tspec = P(None, None)  # tables: [B, MB]
    vspec = P(None)  # kv_len / layer / write_index
    if mode == "decode":
        if q8:
            return (hspec, aspec, aspec, sspec, sspec, tspec, vspec, vspec), hspec
        return (hspec, aspec, aspec, tspec, vspec, vspec), hspec
    if mode == "chunk":
        if q8:
            return (
                (hspec, aspec, aspec, sspec, sspec, tspec, vspec, vspec,
                 vspec),
                hspec,
            )
        return (hspec, aspec, aspec, tspec, vspec, vspec, vspec), hspec
    raise ValueError(f"paged_partition_specs: unknown mode {mode!r}")


def _gather_paged_layer(
    arena: jax.Array,  # [L, N, K, bs, hd] (or [L, N, K, bs] for scales)
    block_tables: jax.Array,  # [B, MB] int32
    layer: jax.Array,  # [] or [1] int32
) -> jax.Array:
    """``[B, K, MB*bs(, hd)]`` logical view of ONE layer, assembled by
    gathering each row's blocks — the shared helper of the XLA oracles (a
    per-layer gather is MBs; CPU tests and the q8 chunk fallback use it,
    the Pallas kernels never materialize it)."""
    lay = jnp.asarray(layer, jnp.int32).reshape(())
    al = jax.lax.dynamic_index_in_dim(arena, lay, 0, keepdims=False)
    g = jnp.take(al, block_tables, axis=0)  # [B, MB, K, bs(, hd)]
    if g.ndim == 5:
        B, MB, K, bs, hd = g.shape
        return g.transpose(0, 2, 1, 3, 4).reshape(B, K, MB * bs, hd)
    B, MB, K, bs = g.shape
    return g.transpose(0, 2, 1, 3).reshape(B, K, MB * bs)


def paged_decode_attention_xla(
    q: jax.Array,  # [B, 1, H, hd]
    k_arena: jax.Array,  # [L, N, K, bs, hd]
    v_arena: jax.Array,  # [L, N, K, bs, hd]
    block_tables: jax.Array,  # [B, MB]
    kv_len: jax.Array,  # [B]
    layer: jax.Array,  # [] or [1] int32
) -> jax.Array:
    """Dense XLA reference for ``paged_decode_attention`` (oracle; fallback
    off-TPU): gather each row's blocks into a logical [B, K, T', hd] view,
    then the dense decode math over the [0, kv_len) window. Gathered slots
    past the frontier zero out first — they can be null-block junk (and in
    tests deliberately NaN), and 0 * NaN = NaN survives the prob mask."""
    k = _zero_invalid(_gather_paged_layer(k_arena, block_tables, layer), kv_len)[None]
    v = _zero_invalid(_gather_paged_layer(v_arena, block_tables, layer), kv_len)[None]
    B = q.shape[0]
    zero = jnp.zeros((B,), jnp.int32)
    return decode_attention_xla(q, k, v, zero, kv_len, jnp.int32(0))


def _zero_invalid(x: jax.Array, kv_len: jax.Array) -> jax.Array:
    """Zero logical slots >= kv_len of a gathered ``[B, K, T'(, hd)]``
    view (the oracle-side mirror of the kernels' pre-matmul zeroing)."""
    T = x.shape[2]
    ok = jnp.arange(T)[None, None, :] < kv_len[:, None, None]
    if x.ndim == 4:
        ok = ok[..., None]
    return jnp.where(ok, x, 0)


def paged_decode_attention_xla_q8(
    q: jax.Array,  # [B, 1, H, hd]
    k_arena: jax.Array,  # [L, N, K, bs, hd] int8
    v_arena: jax.Array,  # [L, N, K, bs, hd] int8
    k_scale: jax.Array,  # [L, N, K, bs] fp32
    v_scale: jax.Array,  # [L, N, K, bs] fp32
    block_tables: jax.Array,  # [B, MB]
    kv_len: jax.Array,  # [B]
    layer: jax.Array,  # [] or [1] int32
) -> jax.Array:
    """Dense XLA reference for ``paged_decode_attention_q8``: gather +
    window-masked dequant of this layer's blocks, then the bf16 oracle."""
    kd, vd = _dequant_paged_layer(
        k_arena, v_arena, k_scale, v_scale, block_tables, kv_len, layer, q.dtype
    )
    B = q.shape[0]
    zero = jnp.zeros((B,), jnp.int32)
    return decode_attention_xla(q, kd, vd, zero, kv_len, jnp.int32(0))


def _dequant_paged_layer(
    k_arena, v_arena, k_scale, v_scale, block_tables, kv_len, layer, dtype
):
    """Gathered, dequantized ``[1, B, K, T', hd]`` K/V views of one layer
    of an int8 arena. Scales past the frontier zero out under the window
    mask (they can be uninitialized fp32 = NaN; the int8 payload is finite
    by construction), so invalid slots contribute exactly 0."""
    k = _gather_paged_layer(k_arena, block_tables, layer)
    v = _gather_paged_layer(v_arena, block_tables, layer)
    ks = _gather_paged_layer(k_scale, block_tables, layer)
    vs = _gather_paged_layer(v_scale, block_tables, layer)
    T = k.shape[2]
    t_ok = jnp.arange(T)[None, None, :] < kv_len[:, None, None]  # [B, 1, T]
    ks = jnp.where(t_ok, ks, 0.0)
    vs = jnp.where(t_ok, vs, 0.0)
    kd = (k.astype(jnp.float32) * ks[..., None]).astype(dtype)[None]
    vd = (v.astype(jnp.float32) * vs[..., None]).astype(dtype)[None]
    return kd, vd


def paged_chunk_attention_xla(
    q: jax.Array,  # [B, S, H, hd]
    k_arena: jax.Array,  # [L, N, K, bs, hd]
    v_arena: jax.Array,  # [L, N, K, bs, hd]
    block_tables: jax.Array,  # [B, MB]
    kv_len: jax.Array,  # [B]
    layer: jax.Array,  # [] or [1] int32
    write_index: jax.Array,  # [B] int32: per-row logical slot of query 0
) -> jax.Array:
    """Dense XLA reference for ``paged_chunk_attention`` (oracle; fallback
    off-TPU). Offset causality is PER-ROW (``write_index`` is a vector —
    paged rows are right-padded and chunk at their own depths)."""
    k = _zero_invalid(_gather_paged_layer(k_arena, block_tables, layer), kv_len)[None]
    v = _zero_invalid(_gather_paged_layer(v_arena, block_tables, layer), kv_len)[None]
    return _paged_chunk_on_views(q, k, v, kv_len, write_index)


def paged_chunk_attention_xla_q8(
    q: jax.Array,  # [B, S, H, hd]
    k_arena: jax.Array,  # [L, N, K, bs, hd] int8
    v_arena: jax.Array,  # [L, N, K, bs, hd] int8
    k_scale: jax.Array,  # [L, N, K, bs] fp32
    v_scale: jax.Array,  # [L, N, K, bs] fp32
    block_tables: jax.Array,  # [B, MB]
    kv_len: jax.Array,  # [B]
    layer: jax.Array,  # [] or [1] int32
    write_index: jax.Array,  # [B] int32
) -> jax.Array:
    """Dense XLA reference for ``paged_chunk_attention_q8`` (oracle; the
    off-TPU fallback): gather + dequantize ONE layer's blocks, then the
    bf16 oracle. Serving uses the fused kernel above — this path
    materializes a dequantized logical view per layer, spending the
    bandwidth the int8 arena bought."""
    kd, vd = _dequant_paged_layer(
        k_arena, v_arena, k_scale, v_scale, block_tables, kv_len, layer, q.dtype
    )
    return _paged_chunk_on_views(q, kd, vd, kv_len, write_index)


def _paged_chunk_on_views(q, kd, vd, kv_len, write_index):
    """Offset-causal attention over already-gathered [1, B, K, T, hd]
    views (the q8 oracle's tail — shares the masking math above)."""
    B, S, H, hd = q.shape
    K = kd.shape[2]
    G = H // K
    k = kd[0]
    v = vd[0]
    T = k.shape[2]
    qg = q.reshape(B, S, K, G, hd)
    s = jnp.einsum("bqkgd,bktd->bkgqt", qg, k, preferred_element_type=jnp.float32)
    s = s * (hd**-0.5)
    wi = jnp.broadcast_to(jnp.asarray(write_index, jnp.int32), (B,))
    q_pos = wi[:, None] + jnp.arange(S)[None, :]
    t_pos = jnp.arange(T)
    ok = t_pos[None, None, :] < kv_len[:, None, None]
    ok = ok & (t_pos[None, None, :] <= q_pos[:, :, None])
    s = jnp.where(ok[:, None, None, :, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(ok[:, None, None, :, :], p, 0.0)
    o = jnp.einsum(
        "bkgqt,bktd->bqkgd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return o.reshape(B, S, H, hd).astype(q.dtype)


def chunk_attention_xla_q8(
    q: jax.Array,  # [B, S, H, hd]
    k_cache: jax.Array,  # [L, B, K, T, hd] int8
    v_cache: jax.Array,  # [L, B, K, T, hd] int8
    k_scale: jax.Array,  # [L, B, K, T] fp32
    v_scale: jax.Array,  # [L, B, K, T] fp32
    kv_start: jax.Array,  # [B]
    kv_len: jax.Array,  # [B]
    layer: jax.Array,  # [] or [1] int32
    write_index: jax.Array,  # [] int32
) -> jax.Array:
    """Dense XLA reference for ``chunk_prefill_attention_q8`` (oracle; CPU
    path). Dequantizes THIS layer's cache slice and reuses the bf16 oracle."""
    kd = dequantize_layer_slice(k_cache, k_scale, layer, kv_start, kv_len, q.dtype)
    vd = dequantize_layer_slice(v_cache, v_scale, layer, kv_start, kv_len, q.dtype)
    return chunk_attention_xla(q, kd, vd, kv_start, kv_len, jnp.int32(0), write_index)


def decode_attention_xla_q8(
    q: jax.Array,  # [B, 1, H, hd]
    k_cache: jax.Array,  # [L, B, K, T, hd] int8
    v_cache: jax.Array,  # [L, B, K, T, hd] int8
    k_scale: jax.Array,  # [L, B, K, T] fp32
    v_scale: jax.Array,  # [L, B, K, T] fp32
    kv_start: jax.Array,  # [B]
    kv_len: jax.Array,  # [B]
    layer: jax.Array,  # [] or [1] int32
) -> jax.Array:
    """Dense XLA reference for ``decode_attention_q8`` (oracle; CPU path).
    Dequantizes THIS layer's cache slice and reuses the bf16 oracle."""
    kd = dequantize_layer_slice(k_cache, k_scale, layer, kv_start, kv_len, q.dtype)
    vd = dequantize_layer_slice(v_cache, v_scale, layer, kv_start, kv_len, q.dtype)
    return decode_attention_xla(q, kd, vd, kv_start, kv_len, jnp.int32(0))
