"""Device-resident vector index with atomic persistence."""

from rag_llm_k8s_tpu.index.store import SearchResult, VectorStore

__all__ = ["SearchResult", "VectorStore"]
