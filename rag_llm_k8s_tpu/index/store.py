"""Device-resident vector store — the framework's ``faiss.IndexFlatL2`` +
pickle-metadata replacement, with the reference's concurrency bugs fixed.

Reference behavior being replaced (/root/reference/llm/rag.py):
- ``IndexFlatL2`` create/add/search/serialize — rag.py:61,80,116,62,82
- pickled metadata sidecar — rag.py:63-64,82-84
- **data race**: ``update_index`` is an unlocked read-modify-write of two
  files, reachable concurrently from ``/upload_pdf`` (rag.py:68-86,141) —
  fixed here by a single-writer lock around all mutation.
- **boot duplication**: ingest re-runs on every pod start and unconditionally
  appends, duplicating every chunk in the persisted index (survey §3.1) —
  fixed here by content-hash dedup.
- **non-atomic persistence**: ``faiss.write_index`` + a separate pickle can
  desync on crash — fixed by write-temp-then-rename of a single snapshot
  (plus a generation number for observability).

Search runs on device: embeddings live as a padded ``[N_pad, D]`` fp32 array
(padded so the executable shape only changes when the index outgrows its
bucket), queried through the fused Pallas kNN kernel on TPU (XLA fallback
elsewhere) — ``ops/knn.py``.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rag_llm_k8s_tpu.ops.knn import BIG, knn_topk
from rag_llm_k8s_tpu.resilience import faults
from rag_llm_k8s_tpu.utils.buckets import next_pow2

_FORMAT_VERSION = 1


def _indexio():
    """The C++ snapshot codec (native/indexio.cpp): CRC32-verified payload,
    fsync-before-rename durability. None ⇒ numpy .npy fallback (no checksum
    — the codec exists because faiss's writer and np.save both lack one)."""
    try:
        from rag_llm_k8s_tpu.native import load_library
    except ImportError:
        return None
    import ctypes

    lib = load_library("indexio")
    if lib is None:
        return None
    lib.indexio_write.restype = ctypes.c_int32
    lib.indexio_write.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float),
    ]
    lib.indexio_read_header.restype = ctypes.c_int32
    lib.indexio_read_header.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)
    ]
    lib.indexio_read.restype = ctypes.c_int32
    lib.indexio_read.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64
    ]
    return lib


def _save_vectors(vec_path: str, vectors: np.ndarray, generation: int) -> str:
    """Persist the fp32 payload. Native codec when available (checksummed,
    fsynced, atomic); tmp-then-rename .npy otherwise. Returns the format
    actually written ("indexio" | "npy") for the metadata record."""
    import ctypes

    lib = _indexio()
    vectors = np.ascontiguousarray(vectors, np.float32)
    if lib is not None:
        rc = lib.indexio_write(
            vec_path.encode(), vectors.shape[1] if vectors.ndim == 2 else 0,
            vectors.shape[0], generation,
            vectors.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        if rc == 0:
            return "indexio"
        import logging

        logging.getLogger(__name__).warning(
            "native index write failed (rc=%d); falling back to npy", rc
        )
    dir_ = os.path.dirname(vec_path) or "."
    fd, tmp = tempfile.mkstemp(dir=dir_, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.save(f, vectors)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, vec_path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return "npy"


def _load_vectors(vec_path: str, dim: int) -> np.ndarray:
    """Load the payload, auto-detecting format: the native codec's magic
    first (CRC-verified — corruption raises instead of silently mis-ranking
    every future search), .npy otherwise (including pre-codec snapshots)."""
    import ctypes

    with open(vec_path, "rb") as f:
        magic = f.read(8)
    if magic == b"TPURIDX1":
        lib = _indexio()
        if lib is None:
            raise RuntimeError(
                f"{vec_path} is a native-codec snapshot but no C++ toolchain "
                "is available to read it"
            )
        hdr = (ctypes.c_int64 * 4)()
        rc = lib.indexio_read_header(vec_path.encode(), hdr)
        if rc != 0:
            raise ValueError(f"index payload header corrupt ({vec_path}, rc={rc})")
        f_dim, count, _gen, payload = hdr[0], hdr[1], hdr[2], hdr[3]
        if f_dim != dim:
            raise ValueError(f"index payload dim {f_dim} != expected {dim}")
        # the CRC covers the payload, not the header: a corrupted header
        # must fail HERE, not size the read buffer (count/payload mismatch
        # would otherwise hand indexio_read a larger byte count than the
        # numpy allocation — heap overflow, not a clean error)
        if count < 0 or payload != count * dim * 4:
            raise ValueError(
                f"index payload header inconsistent ({vec_path}: count={count}, "
                f"dim={dim}, payload_bytes={payload}) — snapshot is corrupt"
            )
        out = np.empty((count, dim), np.float32)
        rc = lib.indexio_read(
            vec_path.encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            payload,
        )
        if rc != 0:
            raise ValueError(
                f"index payload failed CRC/read ({vec_path}, rc={rc}) — "
                "snapshot is corrupt"
            )
        return out
    return np.load(vec_path)


@jax.jit
def _dev_append(emb, norms, rows, n_old, n_real):
    """Produce a NEW snapshot with ``rows[:n_real]`` written at ``n_old``.

    Deliberately NOT donated: concurrent searches hold references to the old
    ``(emb, norms)`` pair outside the store lock — immutable snapshots are
    the concurrency contract, and donation would invalidate them mid-search.
    The cost is one device-side O(capacity) buffer copy per ingest batch
    (HBM-to-HBM, ~ms even at GB scale — dwarfed by the embedding forward);
    the host->device transfer stays O(batch). ``rows`` is padded to a
    power-of-two row count to bound executable variants; padding rows carry
    BIG norms so they stay unrankable until a later add overwrites them."""
    emb = jax.lax.dynamic_update_slice(emb, rows.astype(emb.dtype), (n_old, 0))
    real = jnp.arange(rows.shape[0]) < n_real
    row_norms = jnp.where(real, jnp.sum(rows * rows, axis=1), BIG)[None, :]
    norms = jax.lax.dynamic_update_slice(norms, row_norms, (0, n_old))
    return emb, norms


@jax.jit
def _tok_append(toks, lens, rows, rlens, n_old):
    """Splice freshly tokenized chunk rows into the token sidecar at
    ``n_old`` — the token-plane sibling of ``_dev_append`` (same O(batch)
    transfer + immutable-pair contract; not donated for the same reason)."""
    toks = jax.lax.dynamic_update_slice(toks, rows, (n_old, 0))
    lens = jax.lax.dynamic_update_slice(lens, rlens, (n_old,))
    return toks, lens


@dataclass
class SearchResult:
    """One hit: metadata dict + squared-L2 distance (faiss-parity score);
    ``row`` is the store row id (lets consumers reach the cached token row
    without re-tokenizing — -1 when externally constructed)."""

    metadata: Dict
    distance: float
    row: int = -1


def _content_hash(metadata: Dict) -> str:
    """Dedup key: document identity + chunk text (NOT the embedding — vectors
    for identical content are regenerated identically by the same encoder;
    encoder CHANGES are handled by the store-level ``fingerprint``)."""
    h = hashlib.sha256()
    h.update(str(metadata.get("filename", "")).encode())
    h.update(str(metadata.get("chunk_id", "")).encode())
    h.update(str(metadata.get("text", "")).encode())
    return h.hexdigest()


def _pad_bucket(n: int, minimum: int = 512) -> int:
    return max(minimum, next_pow2(n))


class VectorStore:
    """Append-only exact-kNN store. Thread-safe: one writer lock serializes
    mutation + persistence; searches read an immutable device snapshot."""

    def __init__(self, dim: int, path: Optional[str] = None, fingerprint: str = ""):
        self.dim = dim
        self.path = path
        # identifies the embedder that produced the stored vectors; a mismatch
        # at open time means the index is stale (e.g. swapped encoder weights)
        self.fingerprint = fingerprint
        self._lock = threading.RLock()
        self._vectors = np.zeros((0, dim), np.float32)
        self._metadata: List[Dict] = []
        self._hashes: set = set()
        # per-row content hashes, index-aligned with _metadata: the STABLE
        # chunk identity (survives restarts, reloads and re-ingest order)
        # that the KV prefix cache keys segment blocks by
        self._row_hashes: List[str] = []
        self.generation = 0
        # device snapshot: padded [cap, D] embeddings + [1, cap] squared
        # norms. IMMUTABLE pair: mutation swaps in a NEW pair (O(batch)
        # host transfer + an on-device copy — see _dev_append, never
        # in-place/donated: concurrent searches hold the old pair); only
        # outgrowing the padded bucket forces a full re-upload.
        self._dev: Optional[Tuple[jax.Array, jax.Array]] = None
        # observability: ingest-path transfer accounting (tests assert on it)
        self.transfer_stats = {"row_update_batches": 0, "full_uploads": 0}
        # optional chunk-token sidecar for the single-fetch serving path:
        # per-row LLM token ids of each chunk's prompt segment, index-aligned
        # with the vectors, materialized on device via token_snapshot() so a
        # /query's retrieved rows can be assembled into the prompt ON DEVICE
        # (the ids never cross to the host before generation). Populated by
        # the token_source callback at add() time; rows missing it (e.g.
        # after load()) re-tokenize lazily from metadata in token_snapshot.
        self._token_fn = None
        self._chunk_tokens: List[Optional[np.ndarray]] = []
        self._tok_dev: Optional[Tuple[jax.Array, jax.Array]] = None
        self._tok_count = 0  # rows reflected in _tok_dev
        self._tok_build_lock = threading.Lock()  # serializes sidecar builds

    # ------------------------------------------------------------------
    # mutation (single-writer)
    # ------------------------------------------------------------------
    def add(
        self,
        vectors: Sequence[np.ndarray],
        metadata: Sequence[Dict],
        dedup: bool = True,
    ) -> int:
        """Append vectors; returns how many were actually added (content-hash
        duplicates are skipped so boot-time re-ingest is idempotent)."""
        if len(vectors) != len(metadata):
            raise ValueError("vectors and metadata length mismatch")
        with self._lock:  # dedup check and append are one atomic step
            fresh_v, fresh_m, fresh_h = [], [], []
            for v, m in zip(vectors, metadata):
                v = np.asarray(v, np.float32).reshape(-1)
                if v.shape[0] != self.dim:
                    raise ValueError(f"vector dim {v.shape[0]} != index dim {self.dim}")
                h = _content_hash(m)
                if dedup and (h in self._hashes or h in fresh_h):
                    continue
                fresh_v.append(v)
                fresh_m.append(dict(m))
                fresh_h.append(h)
            if not fresh_v:
                return 0
            n_old = len(self._metadata)
            new_rows = np.stack(fresh_v)
            self._vectors = np.concatenate([self._vectors, new_rows], axis=0)
            self._metadata.extend(fresh_m)
            self._hashes.update(fresh_h)
            self._row_hashes.extend(fresh_h)
            # token rows fill LAZILY in token_snapshot (tokenizing here would
            # tax the ingest hot path); the live sidecar pair stays — its
            # row-coverage counter marks it stale and the next snapshot
            # call splices just the new rows
            self._chunk_tokens.extend([None] * len(fresh_m))
            self.generation += 1
            self._append_device_rows(n_old, new_rows)
        return len(fresh_v)

    def _append_device_rows(self, n_old: int, new_rows: np.ndarray):
        """Write freshly added rows into the live device snapshot in place;
        drop the snapshot only when the padded bucket is outgrown (the next
        search rebuilds at the larger bucket). Caller holds the lock."""
        if self._dev is None:
            return  # nothing materialized yet; first search uploads once
        emb, norms = self._dev
        n_real = new_rows.shape[0]
        n_pad = next_pow2(max(n_real, 1))
        if n_old + n_pad > emb.shape[0]:
            self._dev = None  # bucket growth: full re-upload on next search
            return
        rows = np.zeros((n_pad, new_rows.shape[1]), np.float32)
        rows[:n_real] = new_rows
        # one O(batch) host->device transfer into a NEW snapshot pair —
        # deliberately not donated/in-place (see _dev_append: concurrent
        # searches hold the old immutable pair; the device-side O(capacity)
        # copy is the price of that contract)
        self._dev = _dev_append(
            emb, norms, jnp.asarray(rows), jnp.int32(n_old), jnp.int32(n_real)
        )
        self.transfer_stats["row_update_batches"] += 1

    # ------------------------------------------------------------------
    # search (on device)
    # ------------------------------------------------------------------
    def device_snapshot(self) -> Tuple[jax.Array, jax.Array]:
        """The immutable device pair ``(emb [cap, D] fp32, sq_norms [1, cap])``
        consumers rank against (e.g. the server's fused embed+kNN call).
        Contract: rows past ``ntotal`` are zero vectors whose norms are BIG,
        so they can never enter a top-k with ``k <= ntotal``; the pair is
        never mutated — mutation swaps in a new pair under the lock."""
        with self._lock:
            if self._dev is not None:
                return self._dev
            n = len(self._metadata)
            n_pad = _pad_bucket(max(n, 1))
            emb = np.zeros((n_pad, self.dim), np.float32)
            emb[:n] = self._vectors
            norms = np.full((1, n_pad), BIG, np.float32)
            norms[0, :n] = (self._vectors**2).sum(axis=1)
            self._dev = (jnp.asarray(emb), jnp.asarray(norms))
            self.transfer_stats["full_uploads"] += 1
            return self._dev

    def attach_token_source(self, fn) -> None:
        """Configure the chunk→LLM-token-ids callback (``fn(metadata) ->
        list[int]``) behind the single-fetch serving path. Idempotent; a
        CHANGED source drops cached rows (they were produced by the old
        one). Sources carrying an equal ``cache_key`` attribute are treated
        as the same source (a new service attaching a fresh closure over
        the same tokenizer keeps the rows)."""
        with self._lock:
            old = self._token_fn
            if old is not None and old is not fn:
                okey = getattr(old, "cache_key", None)
                nkey = getattr(fn, "cache_key", None)
                if okey is None or nkey is None or okey != nkey:
                    self._chunk_tokens = [None] * len(self._metadata)
                    self._tok_dev = None
                    self._tok_count = 0
            self._token_fn = fn

    def release_token_device(self) -> None:
        """Drop the device sidecar pair (host rows stay cached) — called by
        a service's shutdown so a long-lived store does not pin sidecar HBM
        for a serving stack that no longer exists. The next snapshot call
        re-uploads from the cached host rows."""
        with self._lock:
            self._tok_dev = None
            self._tok_count = 0

    @staticmethod
    def _build_token_plane(rows, n: int) -> Tuple[jax.Array, jax.Array]:
        """Pad ``rows[:n]`` into a bucketed ``(tokens [cap, Lc], lens [cap])``
        device pair — the ONE place the sidecar's bucketing lives."""
        cap = _pad_bucket(max(n, 1))
        max_len = max((r.shape[0] for r in rows[:n]), default=1)
        lc = _pad_bucket(max(max_len, 1), minimum=128)
        toks = np.zeros((cap, lc), np.int32)
        lens = np.zeros((cap,), np.int32)
        for i, row in enumerate(rows[:n]):
            toks[i, : row.shape[0]] = row
            lens[i] = row.shape[0]
        return jnp.asarray(toks), jnp.asarray(lens)

    def token_snapshot(self, blocking: bool = True):
        """Immutable device pair ``(tokens [cap, Lc] int32, lens [cap] int32)``
        of per-chunk prompt-segment token ids, row-aligned with
        ``device_snapshot()`` — the gather source for device-side prompt
        assembly. Requires ``attach_token_source``.

        INCREMENTAL like the vector path: rows added since the last call
        tokenize (lazily — never inside ``add``) and splice into the live
        pair with an O(batch) transfer (``_tok_append``); only outgrowing
        the (cap, Lc) bucket forces a full re-upload, so executable shapes
        grow O(log N). The service's post-ingest hook calls this so queries
        at most pay one O(batch) splice, never a corpus rebuild.

        Tokenization and device transfers run OUTSIDE the store lock
        (seconds at corpus scale — concurrent searches/ingest must not stall
        behind them). Rows are append-only with stable indices, so a
        mid-build add just means another loop iteration; a mid-build token-
        source swap discards the build. ``_tok_build_lock`` serializes
        builders.

        ``blocking=False`` (the QUERY path's mode): never wait behind —
        or perform — a large build inside a request. Returns the fresh
        pair when available, otherwise None if another thread is mid-build
        (the caller falls back to the host path); when the build lock is
        free the splice/build still runs inline, which is O(new rows) —
        the post-ingest hook keeps that small."""
        with self._lock:
            if self._tok_dev is not None and self._tok_count == len(self._metadata):
                return self._tok_dev
            if self._token_fn is None:
                raise RuntimeError("no token source attached (attach_token_source)")
        if not blocking:
            if not self._tok_build_lock.acquire(blocking=False):
                return None
            try:
                return self._token_snapshot_locked()
            finally:
                self._tok_build_lock.release()
        with self._tok_build_lock:
            return self._token_snapshot_locked()

    def _token_snapshot_locked(self) -> Tuple[jax.Array, jax.Array]:
        """Body of token_snapshot; caller holds ``_tok_build_lock``."""
        while True:
            with self._lock:
                n = len(self._metadata)
                if self._tok_dev is not None and self._tok_count == n:
                    return self._tok_dev
                fn = self._token_fn
                if fn is None:
                    raise RuntimeError(
                        "no token source attached (attach_token_source)"
                    )
                rows = list(self._chunk_tokens)
                metas = list(self._metadata)
                pair, count = self._tok_dev, self._tok_count
            # -- expensive part, no lock held --
            fresh = {
                i: np.asarray(fn(metas[i]), np.int32)
                for i in range(n)
                if rows[i] is None
            }
            for i, r in fresh.items():
                rows[i] = r
            new_rows = rows[count:n]
            n_pad = next_pow2(max(len(new_rows), 1))
            if (
                pair is not None
                # the PADDED write block must fit: dynamic_update_slice
                # CLAMPS an overflowing start index, which would shift
                # the block onto earlier real rows (same guard as the
                # vector sibling _append_device_rows)
                and count + n_pad <= pair[0].shape[0]
                and all(r.shape[0] <= pair[0].shape[1] for r in new_rows)
            ):
                # splice: O(batch) transfer into a NEW pair (the old one
                # stays immutable for concurrent readers)
                lc = int(pair[0].shape[1])
                rpad = np.zeros((n_pad, lc), np.int32)
                rlen = np.zeros((n_pad,), np.int32)
                for j, r in enumerate(new_rows):
                    rpad[j, : r.shape[0]] = r
                    rlen[j] = r.shape[0]
                built = _tok_append(
                    pair[0], pair[1], jnp.asarray(rpad), jnp.asarray(rlen),
                    jnp.int32(count),
                )
                self.transfer_stats["tok_row_splices"] = (
                    self.transfer_stats.get("tok_row_splices", 0) + 1
                )
            else:
                built = self._build_token_plane(rows, n)
                self.transfer_stats["tok_full_uploads"] = (
                    self.transfer_stats.get("tok_full_uploads", 0) + 1
                )
            with self._lock:
                if self._token_fn is not fn:
                    continue  # source swapped mid-build: discard
                # bank the tokenization (append-only, content-stable)
                for i, r in fresh.items():
                    if self._chunk_tokens[i] is None:
                        self._chunk_tokens[i] = r
                self._tok_dev = built
                self._tok_count = n
                if len(self._metadata) == n:
                    return built
            # adds landed mid-build: loop — the committed pair is a
            # valid n-row snapshot; the next pass splices the rest

    def content_key(self, row: int) -> Optional[str]:
        """The stable chunk identity for one store row — the content hash
        its dedup already computes. Restart/reload-stable (derived from
        document + chunk text, never from row order or embeddings), so the
        KV prefix cache can key cached chunk KV blocks on it. None when
        ``row`` is out of range."""
        with self._lock:
            if 0 <= row < len(self._row_hashes):
                return self._row_hashes[row]
            return None

    def cached_token_row(self, row: int) -> Optional[np.ndarray]:
        """The cached token ids for one store row (None when not yet
        tokenized or out of range) — lets the host prompt path reuse the
        sidecar's work instead of re-tokenizing the segment per query."""
        with self._lock:
            if 0 <= row < len(self._chunk_tokens):
                return self._chunk_tokens[row]
            return None

    def token_lengths(self, idxs) -> List[int]:
        """Cached token-row lengths for the given row ids (0 when a row has
        not been tokenized yet) — the host mirror of the device budget rule
        reads these for prefill accounting and context rendering."""
        with self._lock:
            out = []
            for i in idxs:
                row = self._chunk_tokens[int(i)] if int(i) < len(self._chunk_tokens) else None
                out.append(0 if row is None else int(row.shape[0]))
            return out

    def search(self, query: np.ndarray, k: int = 5) -> List[SearchResult]:
        """Exact kNN by squared L2 (parity with rag.py:114-120, including the
        distance values the reference surfaces as 'score')."""
        n = len(self._metadata)
        if n == 0:
            return []
        k_eff = min(k, n)
        emb, norms = self.device_snapshot()
        q = np.asarray(query, np.float32).reshape(1, self.dim)
        dists, idx = knn_topk(jnp.asarray(q), emb, norms, k=k_eff)
        return self.results_at(np.asarray(idx[0]), np.asarray(dists[0]))

    def results_at(self, idx, dists) -> List[SearchResult]:
        """Materialize SearchResults for externally computed (idx, dists) —
        the fused embed+kNN serving path ranks on device and only the final
        k indices ever reach the host."""
        faults.maybe_fail("store_lookup")
        return [
            SearchResult(metadata=self._metadata[int(i)], distance=float(d), row=int(i))
            for d, i in zip(dists, idx)
        ]

    # ------------------------------------------------------------------
    # introspection (parity with GET /index_info, rag.py:183-197)
    # ------------------------------------------------------------------
    @property
    def ntotal(self) -> int:
        return len(self._metadata)

    def info(self) -> Dict:
        with self._lock:
            return {
                "total_vectors": len(self._metadata),
                "dimension": self.dim,
                "total_chunks": len(self._metadata),
                "sample_chunks": [dict(m) for m in self._metadata[:5]],
                "generation": self.generation,
            }

    # ------------------------------------------------------------------
    # persistence (atomic snapshot; replaces faiss file + pickle sidecar)
    # ------------------------------------------------------------------
    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path
        if path is None:
            raise ValueError("no path configured")
        with self._lock:
            payload_meta = {
                "format_version": _FORMAT_VERSION,
                "dim": self.dim,
                "count": len(self._metadata),
                "generation": self.generation,
                "fingerprint": self.fingerprint,
                "metadata": self._metadata,
                "hashes": sorted(self._hashes),
            }
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            dir_ = os.path.dirname(path) or "."
            # vectors (native codec or npy) and metadata (json), each written
            # tmp-then-rename; metadata lands LAST and names the payload it
            # belongs to, so a crash between the renames leaves a usable pair
            vec_path = path + ".vectors.npy"
            payload_meta["vector_format"] = _save_vectors(
                vec_path, self._vectors, self.generation
            )
            fd, tmp = tempfile.mkstemp(dir=dir_, suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(payload_meta, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
            # make the rename itself durable (the codec fsyncs its parent
            # dir for the payload; the metadata rename needs the same)
            dfd = os.open(dir_, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        return path

    @classmethod
    def load(cls, path: str, dim: Optional[int] = None) -> "VectorStore":
        with open(path) as f:
            meta = json.load(f)
        if meta.get("format_version") != _FORMAT_VERSION:
            raise ValueError(f"unsupported index format: {meta.get('format_version')}")
        store = cls(dim=meta["dim"], path=path)
        vectors = _load_vectors(path + ".vectors.npy", meta["dim"])
        count = meta["count"]
        if vectors.shape[0] < count:
            raise ValueError(
                f"index corrupt: metadata says {count} vectors, payload has {vectors.shape[0]}"
            )
        store._vectors = np.asarray(vectors[:count], np.float32)
        store._metadata = list(meta["metadata"])
        # token rows are not persisted: they re-derive from metadata text
        # lazily (token_snapshot) once a token source is attached
        store._chunk_tokens = [None] * len(store._metadata)
        store._hashes = set(meta.get("hashes", []))
        # per-row identities re-derive from metadata (snapshots predating
        # the prefix cache don't persist them; content hashing is cheap)
        store._row_hashes = [_content_hash(m) for m in store._metadata]
        store.generation = meta.get("generation", 0)
        store.fingerprint = meta.get("fingerprint", "")
        if dim is not None and store.dim != dim:
            raise ValueError(f"index dim {store.dim} != expected {dim}")
        return store

    @classmethod
    def open_or_create(
        cls, path: str, dim: int, fingerprint: Optional[str] = None
    ) -> "VectorStore":
        """ensure_index_exists parity (rag.py:57-66): load if present, else
        create empty (persisted on first save). A persisted index whose
        embedder fingerprint doesn't match is discarded — its vectors were
        produced by a different encoder and would silently mis-rank against
        fresh query embeddings."""
        if os.path.exists(path):
            store = cls.load(path, dim=dim)
            if fingerprint is not None and store.fingerprint != fingerprint:
                import logging

                logging.getLogger(__name__).warning(
                    "index at %s was built by a different embedder "
                    "(fingerprint %r != %r); rebuilding fresh",
                    path, store.fingerprint, fingerprint,
                )
                return cls(dim=dim, path=path, fingerprint=fingerprint)
            return store
        return cls(dim=dim, path=path, fingerprint=fingerprint or "")
