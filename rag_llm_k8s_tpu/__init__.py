"""rag_llm_k8s_tpu — a TPU-native RAG-LLM serving framework.

A ground-up JAX/XLA/Pallas re-design of the capability surface of
``oscka/rag-llm-k8s`` (reference: ``/root/reference``): the reference's CPU
``transformers`` + SentenceTransformer + faiss stack behind a Flask server
(``llm/rag.py``) becomes

- a Flax Llama-3.1-8B-Instruct with weights TP-sharded over the ICI mesh
  (``models/llama.py``, ``parallel/sharding.py``),
- an XLA-compiled prefill + KV-cached decode engine with continuous batching
  (``engine/``),
- a Pallas brute-force kNN kernel over HBM-resident chunk embeddings replacing
  ``faiss.IndexFlatL2`` (``ops/knn.py``, ``index/store.py``),
- a Flax bge-m3 (XLM-R) encoder replacing ``SentenceTransformer`` (``models/bge_m3.py``),
- a C++ byte-level BPE tokenizer replacing HF's Rust tokenizers (``tokenizer/``),
- the same HTTP surface — ``/upload_pdf``, ``/generate`` (alias ``/query``),
  ``/index_info`` — plus ``/healthz`` and ``/metrics`` (``server/``).

Subpackage map (SURVEY.md §7):
    core/      mesh + dtype policy + typed config (reference constants as defaults)
    ops/       Pallas kernels: kNN top-k, flash attention, decode attention
    parallel/  sharding rules, collective helpers, ring attention (SP)
    models/    Flax Llama-3.1, bge-m3 encoder, safetensors loaders
    engine/    prefill/decode loop, sampling, KV cache, continuous batching
    index/     device-resident vector store with atomic persistence
    rag/       chunking, PDF extraction, prompt assembly, pipeline
    tokenizer/ BPE (Python + C++ native)
    server/    Flask app (route parity with llm/rag.py)
    utils/     logging, timing, atomic file IO
"""

__version__ = "0.1.0"
