// Native BPE merge loop — the framework's C++ replacement for the hot path of
// HF's Rust `tokenizers` crate (which the reference uses via AutoTokenizer,
// /root/reference/llm/rag.py:25; Rust is unavailable in this build
// environment, so the native component is C++).
//
// Scope: the per-word ranked merge loop — the O(n·m) inner loop that
// dominates encode time. Pre-tokenization (regex) and byte remapping stay in
// Python, which calls in with byte-remapped UTF-8 "words" and gets token ids
// back. Exposed as a C ABI for ctypes (no pybind11 in this environment).
//
// Build: g++ -O2 -shared -fPIC -o libtpu_rag_bpe.so bpe.cpp

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct PairHash {
    size_t operator()(const std::pair<std::string, std::string>& p) const {
        std::hash<std::string> h;
        return h(p.first) * 1000003u ^ h(p.second);
    }
};

struct Bpe {
    std::unordered_map<std::string, int32_t> vocab;
    std::unordered_map<std::pair<std::string, std::string>, int32_t, PairHash> ranks;
    // per-handle word cache: the same pre-tokens recur constantly in prose
    std::unordered_map<std::string, std::vector<int32_t>> cache;
};

// split a UTF-8 string into codepoint-sized chunks
std::vector<std::string> utf8_chars(const char* s) {
    std::vector<std::string> out;
    const unsigned char* p = reinterpret_cast<const unsigned char*>(s);
    while (*p) {
        int len = 1;
        if ((*p & 0xF8) == 0xF0) len = 4;
        else if ((*p & 0xF0) == 0xE0) len = 3;
        else if ((*p & 0xE0) == 0xC0) len = 2;
        out.emplace_back(reinterpret_cast<const char*>(p), len);
        p += len;
    }
    return out;
}

}  // namespace

extern "C" {

void* bpe_create() { return new Bpe(); }

void bpe_destroy(void* h) { delete static_cast<Bpe*>(h); }

void bpe_add_token(void* h, const char* token, int32_t id) {
    static_cast<Bpe*>(h)->vocab.emplace(token, id);
}

void bpe_add_merge(void* h, const char* left, const char* right, int32_t rank) {
    static_cast<Bpe*>(h)->ranks.emplace(std::make_pair(left, right), rank);
}

static void encode_word_into(Bpe* bpe, const std::string& word, std::vector<int32_t>& out);

// Encode one pre-tokenized, byte-remapped word. Returns the number of ids
// written to out_ids (<= max_out), or -1 on overflow.
int32_t bpe_encode_word(void* h, const char* word, int32_t* out_ids, int32_t max_out) {
    Bpe* bpe = static_cast<Bpe*>(h);
    std::vector<std::string> parts = utf8_chars(word);
    if (parts.empty()) return 0;

    // ranked merge loop: repeatedly merge the lowest-rank adjacent pair
    while (parts.size() > 1) {
        int32_t best_rank = INT32_MAX;
        size_t best_i = SIZE_MAX;
        for (size_t i = 0; i + 1 < parts.size(); ++i) {
            auto it = bpe->ranks.find(std::make_pair(parts[i], parts[i + 1]));
            if (it != bpe->ranks.end() && it->second < best_rank) {
                best_rank = it->second;
                best_i = i;
            }
        }
        if (best_i == SIZE_MAX) break;
        parts[best_i] += parts[best_i + 1];
        parts.erase(parts.begin() + best_i + 1);
    }

    int32_t n = 0;
    for (const auto& part : parts) {
        auto it = bpe->vocab.find(part);
        if (it != bpe->vocab.end()) {
            if (n >= max_out) return -1;
            out_ids[n++] = it->second;
        } else {
            // unmergeable unknown: per-char byte tokens where known
            for (const auto& ch : utf8_chars(part.c_str())) {
                auto cit = bpe->vocab.find(ch);
                if (cit != bpe->vocab.end()) {
                    if (n >= max_out) return -1;
                    out_ids[n++] = cit->second;
                }
            }
        }
    }
    return n;
}

// Batched encode: `words_nl` is pre-tokenized words joined by '\n' (the
// byte-level remapping maps the 0x0A byte to a multi-byte codepoint, so a
// raw '\n' never appears inside a remapped word). One ctypes crossing per
// TEXT instead of per word, with a per-handle word cache. Returns ids
// written, or -1 if out_ids is too small (caller grows and retries).
int32_t bpe_encode_words(void* h, const char* words_nl, int32_t* out_ids, int32_t max_out) {
    Bpe* bpe = static_cast<Bpe*>(h);
    const char* p = words_nl;
    int32_t n = 0;
    while (*p) {
        const char* end = strchr(p, '\n');
        std::string word = end ? std::string(p, end - p) : std::string(p);
        p = end ? end + 1 : p + word.size();
        if (word.empty()) continue;
        auto it = bpe->cache.find(word);
        if (it == bpe->cache.end()) {
            std::vector<int32_t> ids;
            encode_word_into(bpe, word, ids);
            if (bpe->cache.size() < 262144) bpe->cache.emplace(word, ids);
            it = bpe->cache.find(word);
            if (it == bpe->cache.end()) {  // cache full: use local
                for (int32_t id : ids) {
                    if (n >= max_out) return -1;
                    out_ids[n++] = id;
                }
                continue;
            }
        }
        for (int32_t id : it->second) {
            if (n >= max_out) return -1;
            out_ids[n++] = id;
        }
    }
    return n;
}

}  // extern "C"

static void encode_word_into(Bpe* bpe, const std::string& word, std::vector<int32_t>& out) {
    std::vector<std::string> parts = utf8_chars(word.c_str());
    while (parts.size() > 1) {
        int32_t best_rank = INT32_MAX;
        size_t best_i = SIZE_MAX;
        for (size_t i = 0; i + 1 < parts.size(); ++i) {
            auto it = bpe->ranks.find(std::make_pair(parts[i], parts[i + 1]));
            if (it != bpe->ranks.end() && it->second < best_rank) {
                best_rank = it->second;
                best_i = i;
            }
        }
        if (best_i == SIZE_MAX) break;
        parts[best_i] += parts[best_i + 1];
        parts.erase(parts.begin() + best_i + 1);
    }
    for (const auto& part : parts) {
        auto it = bpe->vocab.find(part);
        if (it != bpe->vocab.end()) {
            out.push_back(it->second);
        } else {
            for (const auto& ch : utf8_chars(part.c_str())) {
                auto cit = bpe->vocab.find(ch);
                if (cit != bpe->vocab.end()) out.push_back(cit->second);
            }
        }
    }
}
