"""Native (C++) components, built on demand with the system toolchain and
loaded via ctypes. See ``bpe.cpp`` (tokenizer merge loop)."""

from rag_llm_k8s_tpu.native.build import load_library

__all__ = ["load_library"]
