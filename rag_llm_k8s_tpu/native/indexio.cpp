// Checksummed, durable snapshot codec for the vector-index payload.
//
// The reference persists its index through faiss's C++ writer plus a python
// pickle (/root/reference/llm/rag.py:62,82-84) — no checksum, no fsync, two
// files that can desync. This codec is the framework's native counterpart
// for the payload half (survey §2b: "C++ host-side index store for
// serialize/append semantics"): one self-describing file, CRC32-verified on
// read, written tmp-then-fsync-then-rename so a crash at any point leaves
// either the old snapshot or the new one, never a torn file. Metadata stays
// JSON on the python side (human-readable parity with /index_info).
//
// Layout (little-endian):
//   0:8   magic   "TPURIDX1"
//   8:8   dim     (int64)
//  16:8   count   (int64)   rows actually populated
//  24:8   generation (int64)
//  32:8   payload_bytes (int64) == count * dim * 4
//  40:8   crc32 of payload (int64, low 32 bits)
//  48:..  payload: count*dim float32
//
// Driven via ctypes (no pybind11 in this environment); plain C ABI.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

#include <fcntl.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'T', 'P', 'U', 'R', 'I', 'D', 'X', '1'};
constexpr int64_t kHeaderBytes = 48;

struct Crc32Tables {
  // slicing-by-8: 8 derived tables -> one table lookup per byte becomes
  // 8 bytes per loop iteration (~5-8x faster; a multi-GB payload would
  // otherwise spend seconds under the store's writer lock per save)
  uint32_t t[8][256];
  Crc32Tables() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int j = 0; j < 8; j++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (int k = 1; k < 8; k++)
      for (uint32_t i = 0; i < 256; i++)
        t[k][i] = t[0][t[k - 1][i] & 0xFF] ^ (t[k - 1][i] >> 8);
  }
};

uint32_t crc32(const uint8_t* data, int64_t n) {
  // C++11 magic static: thread-safe one-time construction (a racy manual
  // ready-flag could let a second thread read a half-built table and stamp
  // a wrong CRC into a perfectly good snapshot)
  static const Crc32Tables tables;
  const auto& t = tables.t;
  uint32_t c = 0xFFFFFFFFu;
  int64_t i = 0;
  for (; i + 8 <= n; i += 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, data + i, 4);
    std::memcpy(&hi, data + i + 4, 4);
    lo ^= c;
    c = t[7][lo & 0xFF] ^ t[6][(lo >> 8) & 0xFF] ^ t[5][(lo >> 16) & 0xFF] ^
        t[4][lo >> 24] ^ t[3][hi & 0xFF] ^ t[2][(hi >> 8) & 0xFF] ^
        t[1][(hi >> 16) & 0xFF] ^ t[0][hi >> 24];
  }
  for (; i < n; i++) c = t[0][(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// fsync the parent directory so a rename is itself durable — without it a
// power cut after save() can resurrect the OLD payload next to NEW metadata
int fsync_parent(const char* path) {
  std::string dir(path);
  const size_t slash = dir.find_last_of('/');
  dir = (slash == std::string::npos) ? "." : dir.substr(0, slash);
  const int dfd = ::open(dir.c_str(), O_RDONLY);
  if (dfd < 0) return -1;
  const int rc = ::fsync(dfd);
  ::close(dfd);
  return rc;
}

struct Header {
  char magic[8];
  int64_t dim;
  int64_t count;
  int64_t generation;
  int64_t payload_bytes;
  int64_t crc;
};
static_assert(sizeof(Header) == kHeaderBytes, "header must be 48 bytes");

}  // namespace

extern "C" {

// Write a snapshot: tmp file in the same directory, fsync, atomic rename.
// Returns 0 on success, negative errno-style codes on failure.
int32_t indexio_write(const char* path, int64_t dim, int64_t count,
                      int64_t generation, const float* data) {
  const int64_t payload = count * dim * static_cast<int64_t>(sizeof(float));
  Header h;
  std::memcpy(h.magic, kMagic, 8);
  h.dim = dim;
  h.count = count;
  h.generation = generation;
  h.payload_bytes = payload;
  h.crc = crc32(reinterpret_cast<const uint8_t*>(data), payload);

  // unique temp name (pid + monotonic counter): concurrent savers — e.g.
  // two pods on a shared volume, where no in-process lock can help — must
  // never truncate each other's half-written temp; each writes its own
  // file and the last complete rename wins, like the python mkstemp path
  static int counter = 0;
  const std::string tmp = std::string(path) + ".tmp." +
                          std::to_string(::getpid()) + "." +
                          std::to_string(__atomic_add_fetch(&counter, 1, __ATOMIC_SEQ_CST));
  const int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_EXCL, 0644);
  if (fd < 0) return -1;
  bool ok = ::write(fd, &h, sizeof(h)) == static_cast<ssize_t>(sizeof(h));
  const uint8_t* p = reinterpret_cast<const uint8_t*>(data);
  int64_t left = payload;
  while (ok && left > 0) {
    const ssize_t n = ::write(fd, p, static_cast<size_t>(left));
    if (n <= 0) { ok = false; break; }
    p += n;
    left -= n;
  }
  // durability: payload reaches the platter/SSD BEFORE the rename publishes
  // it — np.save + rename alone can lose the payload on power cut
  ok = ok && ::fsync(fd) == 0;
  ok = (::close(fd) == 0) && ok;
  if (!ok) { ::unlink(tmp.c_str()); return -2; }
  if (::rename(tmp.c_str(), path) != 0) { ::unlink(tmp.c_str()); return -3; }
  if (fsync_parent(path) != 0) return -7;  // rename published but not durable
  return 0;
}

// Read the header: out = [dim, count, generation, payload_bytes].
// Returns 0 on success, -1 open failure, -4 bad magic/short header.
int32_t indexio_read_header(const char* path, int64_t* out) {
  const int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  Header h;
  const bool ok = ::read(fd, &h, sizeof(h)) == static_cast<ssize_t>(sizeof(h));
  ::close(fd);
  if (!ok || std::memcmp(h.magic, kMagic, 8) != 0) return -4;
  out[0] = h.dim;
  out[1] = h.count;
  out[2] = h.generation;
  out[3] = h.payload_bytes;
  return 0;
}

// Read + CRC-verify the payload into caller-allocated memory of
// payload_bytes (from indexio_read_header). Returns 0 ok, -1 open,
// -4 bad header, -5 short payload, -6 checksum mismatch (corruption).
int32_t indexio_read(const char* path, float* data, int64_t payload_bytes) {
  const int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  Header h;
  if (::read(fd, &h, sizeof(h)) != static_cast<ssize_t>(sizeof(h)) ||
      std::memcmp(h.magic, kMagic, 8) != 0 || h.payload_bytes != payload_bytes) {
    ::close(fd);
    return -4;
  }
  uint8_t* p = reinterpret_cast<uint8_t*>(data);
  int64_t left = payload_bytes;
  while (left > 0) {
    const ssize_t n = ::read(fd, p, static_cast<size_t>(left));
    if (n <= 0) { ::close(fd); return -5; }
    p += n;
    left -= n;
  }
  ::close(fd);
  const uint32_t got = crc32(reinterpret_cast<const uint8_t*>(data), payload_bytes);
  if (static_cast<int64_t>(got) != h.crc) return -6;
  return 0;
}

}  // extern "C"
