"""On-demand compilation + ctypes loading of the C++ components.

No pybind11 in this environment (and no Rust), so native code exposes a plain
C ABI compiled with the system g++ and is driven through ctypes. Libraries
build once into the package directory and are cached by source mtime.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_CACHE: dict = {}


def load_library(name: str) -> Optional[ctypes.CDLL]:
    """Build (if stale) and load ``lib<name>.so`` from ``<name>.cpp``.

    Returns None when no C++ toolchain is available — callers fall back to
    their pure-Python implementations.
    """
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        src = os.path.join(_DIR, f"{name}.cpp")
        lib_path = os.path.join(_DIR, f"lib{name}.so")
        try:
            if not os.path.exists(lib_path) or os.path.getmtime(lib_path) < os.path.getmtime(src):
                cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", lib_path, src]
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
                logger.info("built native library %s", lib_path)
            lib = ctypes.CDLL(lib_path)
        except (OSError, subprocess.SubprocessError) as e:
            logger.warning("native %s unavailable (%s); using pure-Python path", name, e)
            lib = None
        _CACHE[name] = lib
        return lib
