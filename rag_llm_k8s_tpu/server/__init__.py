"""HTTP serving layer (Flask) — route parity with the reference's llm/rag.py."""

from rag_llm_k8s_tpu.server.app import RagService, create_app

__all__ = ["RagService", "create_app"]
